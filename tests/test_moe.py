"""MoE / expert-parallel tests (models/moe.py).

The reference has no MoE (sync-DP only, README.md:14-21); this tier is
validated the framework's own way: exact math checks on the routing
(dense-equivalence limit, capacity dropping, load-balance loss), then
real train steps on the 8-device CPU mesh under both engines, including
genuinely expert-sharded params on a (data, expert) mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.models.moe import MoEMlpBlock
from distributeddeeplearning_tpu.models.sharding import (
    LOGICAL_RULES,
    rules_for_mesh,
)
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.training import create_train_state, make_train_step
from distributeddeeplearning_tpu.training.pjit_step import (
    create_sharded_train_state,
    make_pjit_train_step,
)
from distributeddeeplearning_tpu.training.train_step import replicate_state


def _moe_layer(e=4, k=2, cf=8.0, dtype=jnp.float32, mlp_dim=32):
    # cf=8.0: capacity ≥ every token's every choice — nothing dropped.
    return MoEMlpBlock(
        num_experts=e, mlp_dim=mlp_dim, num_selected=k,
        capacity_factor=cf, dtype=dtype,
    )


def test_identical_experts_match_dense_mlp():
    """With every expert holding the same weights and no dropping, the
    gate-weighted combine sums to 1 — the MoE layer must equal the plain
    MLP with those weights."""
    layer = _moe_layer()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
    import flax.linen as nn

    variables = layer.init(jax.random.PRNGKey(0), x, train=False)
    p = jax.device_get(nn.unbox(variables["params"]))
    for name in ("w1", "w2", "b1", "b2"):
        p[name] = np.broadcast_to(p[name][:1], p[name].shape).copy()
    out = layer.apply({"params": p}, x, train=False)

    w1, b1, w2, b2 = p["w1"][0], p["b1"][0], p["w2"][0], p["b2"][0]
    ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_capacity_drops_overflow_tokens():
    """Force all tokens onto expert 0 with tiny capacity: tokens beyond
    the buffer fall through with zero output (the residual path)."""
    layer = MoEMlpBlock(num_experts=2, mlp_dim=8, num_selected=1,
                        capacity_factor=0.25, dtype=jnp.float32)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 8, 4).astype(np.float32))
    import flax.linen as nn
    variables = layer.init(jax.random.PRNGKey(0), x, train=False)
    p = jax.device_get(nn.unbox(variables["params"]))
    out = np.asarray(layer.apply({"params": p}, x, train=False))
    # capacity = ceil(1*8/2*0.25) = 1 slot per expert: at most E*c = 2 of
    # the 8 tokens get processed; every overflow token's output is exactly
    # zero (it falls through the block's residual connection).
    nonzero_rows = int((np.abs(out[0]).sum(-1) > 1e-9).sum())
    assert 1 <= nonzero_rows <= 2, nonzero_rows
    # and the first token routed to each expert is among the survivors:
    # every zero row must be a genuine drop, not a numerically-zero output
    assert out.shape == (1, 8, 4)


def test_aux_loss_sown_and_skew_sensitive():
    """Sown load-balance loss ≈ weight at uniform routing, larger when the
    router collapses onto one expert."""
    layer = _moe_layer(e=4, k=1)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 16, 16).astype(np.float32))
    import flax.linen as nn
    variables = layer.init(jax.random.PRNGKey(3), x, train=False)
    p = jax.device_get(nn.unbox(variables["params"]))
    p_uniform = dict(p, router=np.zeros_like(p["router"]))
    _, mut = layer.apply(
        {"params": p_uniform}, x, train=False, mutable=["losses"]
    )
    (aux_uniform,) = jax.tree.leaves(mut["losses"])
    # uniform: E * Σ f·P = E * E*(1/E · 1/E) = 1 (times the weight). f
    # depends on argmax tie-breaking, but P is exactly uniform.
    assert 0.0 < float(aux_uniform) <= 2 * layer.aux_loss_weight
    p_skew = dict(p, router=np.zeros_like(p["router"]))
    p_skew["router"][:, 0] = 100.0
    # all-positive features × (+100 on expert 0) → every token's softmax
    # collapses onto expert 0: f = (1,0,..), P ≈ (1,0,..) → aux ≈ weight·E
    _, mut = layer.apply(
        {"params": p_skew}, jnp.abs(x), train=False, mutable=["losses"]
    )
    (aux_skew,) = jax.tree.leaves(mut["losses"])
    assert float(aux_skew) > 2.0 * float(aux_uniform)


def test_moe_lm_trains_dp(mesh8):
    """lm_moe registry entry trains under the shard_map DP engine; the
    aux loss reaches the objective and expert weights receive gradient."""
    vocab, t = 32, 8
    model = get_model(
        "lm_moe_tiny", num_classes=vocab, dtype=jnp.float32,
        max_seq_len=t, moe_experts=4,
    )
    assert isinstance(model, TransformerLM) and model.moe_experts == 4
    cfg = TrainConfig(model="lm_moe_tiny", num_classes=vocab,
                      batch_size_per_device=2, weight_decay=0.0)
    tx = optax.sgd(0.1)
    state = replicate_state(
        create_train_state(model, cfg, tx, input_shape=(1, t),
                           input_dtype=jnp.int32),
        mesh8,
    )
    w1_before = np.asarray(
        jax.device_get(state.params["block1"]["moe"]["w1"]))
    rng = np.random.RandomState(0)
    rows = rng.randint(0, vocab, size=(16, t + 1)).astype(np.int32)
    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh8)
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    w1_after = np.asarray(jax.device_get(state.params["block1"]["moe"]["w1"]))
    assert np.abs(w1_after - w1_before).max() > 0  # experts actually learn


def test_moe_lm_ep_sharding_pjit(devices):
    """EP is real: on a (data, expert) mesh the GSPMD engine shards the
    expert dimension of every MoE weight and the step trains."""
    mesh = create_mesh(axes=("data", "expert"), shape=(2, 4))
    vocab, t = 32, 8
    model = TransformerLM(
        variant="tiny", vocab_size=vocab, max_seq_len=t,
        dtype=jnp.float32, moe_experts=4,
    )
    cfg = TrainConfig(num_classes=vocab, batch_size_per_device=2,
                      weight_decay=0.0)
    tx = optax.sgd(0.1)
    state = create_sharded_train_state(
        model, cfg, tx, mesh, LOGICAL_RULES,
        input_shape=(1, t), input_dtype=jnp.int32,
    )
    moe = state.params["block1"]["moe"]
    assert tuple(moe["w1"].sharding.spec)[:1] == ("expert",)
    assert tuple(moe["w2"].sharding.spec)[:1] == ("expert",)
    assert tuple(moe["router"].sharding.spec) in ((None, "expert"), ("expert",))
    rng = np.random.RandomState(0)
    rows = rng.randint(0, vocab, size=(4, t + 1)).astype(np.int32)
    step = make_pjit_train_step(model, tx, mesh, cfg, donate_state=False)
    with mesh:
        batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh)
        s = state
        losses = []
        for _ in range(3):
            s, metrics = step(s, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_ep_matches_dense_replicated(devices):
    """The sharded-expert step computes the same update as the same model
    on a single device (routing is deterministic; EP only moves where
    experts live)."""
    mesh_ep = create_mesh(axes=("data", "expert"), shape=(2, 4))
    mesh_1 = create_mesh(devices=jax.devices()[:1])
    vocab, t = 16, 8
    model = TransformerLM(
        variant="tiny", vocab_size=vocab, max_seq_len=t,
        dtype=jnp.float32, moe_experts=4,
    )
    cfg = TrainConfig(num_classes=vocab, batch_size_per_device=2,
                      weight_decay=0.0)
    tx = optax.sgd(0.1)
    rng = np.random.RandomState(3)
    rows = rng.randint(0, vocab, size=(4, t + 1)).astype(np.int32)

    results = []
    for mesh in (mesh_ep, mesh_1):
        state = create_sharded_train_state(
            model, cfg, tx, mesh, LOGICAL_RULES,
            input_shape=(1, t), input_dtype=jnp.int32,
        )
        step = make_pjit_train_step(model, tx, mesh, cfg, donate_state=False)
        with mesh:
            s, metrics = step(state, shard_batch((rows[:, :-1], rows[:, 1:]), mesh))
        results.append((float(metrics["loss"]), jax.device_get(s.params)))
    assert np.isclose(results[0][0], results[1][0], rtol=1e-5)
    for a, b in zip(jax.tree.leaves(results[0][1]), jax.tree.leaves(results[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_moe_env_knob():
    """MOE_EXPERTS reaches the model through the shared
    config.model_kwargs() construction point; conv models ignore it."""
    cfg = TrainConfig.from_env({"MODEL": "lm_tiny", "MOE_EXPERTS": "4"})
    assert cfg.moe_experts == 4
    m = get_model(cfg.model, **cfg.model_kwargs())
    assert isinstance(m, TransformerLM) and m.moe_experts == 4
    m2 = get_model("resnet18", **cfg.model_kwargs())
    assert m2.__class__.__name__ == "ResNet"
    # and lm_moe_* defaults to 8 experts with no knob set
    cfg2 = TrainConfig.from_env({"MODEL": "lm_moe_tiny"})
    m3 = get_model(cfg2.model, **cfg2.model_kwargs())
    assert m3.moe_experts == 8


def test_rules_for_mesh_projection(devices):
    mesh_dp = create_mesh(devices=jax.devices())  # data only
    projected = dict(rules_for_mesh(mesh_dp))
    assert projected["expert"] is None
    assert projected["heads"] is None
    assert projected["batch"] == ("data",)
    mesh_ep = create_mesh(axes=("data", "expert"), shape=(2, 4))
    projected = dict(rules_for_mesh(mesh_ep))
    assert projected["expert"] == "expert"
    assert projected["heads"] is None


def test_top1_router_gets_output_gradient():
    """Switch-style top-1 routing: the combine weight is the RAW gate
    probability, so the router kernel receives gradient through the
    output path even with the aux loss disabled (ADVICE r2: renormalized
    top-1 weights were identically 1 — gradient only via aux loss)."""
    import flax.linen as nn

    from distributeddeeplearning_tpu.models.moe import MoEMlpBlock

    layer = MoEMlpBlock(num_experts=4, mlp_dim=8, num_selected=1,
                   aux_loss_weight=0.0, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 4), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(1), x, train=False)

    def out_sum(params):
        y, _ = layer.apply(
            {"params": params}, x, train=True, mutable=["losses"]
        )
        return jnp.sum(y)

    grads = jax.grad(out_sum)(variables["params"])
    flat = jax.tree_util.tree_leaves_with_path(grads)
    router = [g for p, g in flat if "router" in str(p).lower() or "gate" in str(p).lower()]
    assert router, [str(p) for p, _ in flat]
    assert any(float(jnp.abs(g).max()) > 0 for g in router)
