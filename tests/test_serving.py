"""Continuous-batching serving oracles (serving/ + inference glue).

The serving tier's whole value rests on two claims, both pinned here:

1. **Parity** — a request co-decoded on the slot pool emits *bitwise*
   the tokens sequential ``inference.generate`` emits for the same
   (prompt, config, rng), whatever the co-scheduling: staggered joins,
   mixed prompt lengths/buckets, neighbours hitting eos, mid-stream
   cancellations freeing slots that are immediately re-admitted into.
   Greedy and seeded sampling both.
2. **Zero recompiles** — the engine's program set is closed at warmup
   (``bucket_count + 1`` programs) and an admission/eviction churn
   triggers no backend compile (counted via jax's
   ``backend_compile_duration`` monitoring event, not inferred).

Plus the host-side key schedule (``serving.keys`` — numpy threefry)
pinned bitwise against this process's ``jax.random``, the per-slot
sampler against ``inference._sample`` across the config matrix, and the
scheduler lifecycle (backpressure, deadlines, cancel, drain).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.inference import _sample, generate
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.serving import (
    QueueFull,
    ReqSpec,
    Request,
    ServeConfig,
    Server,
    SlotEngine,
)
from distributeddeeplearning_tpu.serving import keys as keylib
from distributeddeeplearning_tpu.serving.sampling import sample_slot

VOCAB, MAX_LEN = 64, 32
BUCKETS = (4, 8, 16)


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    import flax.linen as nn

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


@pytest.fixture(scope="module")
def _engine(model, params):
    eng = SlotEngine(
        model, params, num_slots=4, max_len=MAX_LEN, buckets=BUCKETS
    )
    eng.warmup()
    return eng


@pytest.fixture
def engine(_engine):
    """The shared warmed engine, guaranteed empty per test."""
    for s in _engine.active_slots:
        _engine.release(s)
    yield _engine
    for s in _engine.active_slots:
        _engine.release(s)


def _prompt(rng, n):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _assert_request_parity(h, model, params):
    """One handle's stream vs sequential generate at the same config.

    Finished requests must match up to their own length (eos cuts the
    stream; generate pads the remainder); cancelled/deadline-evicted
    ones must still be an exact *prefix* — eviction can truncate a
    stream but never corrupt it."""
    r = h.request
    rng = (
        jax.random.PRNGKey(r.rng) if isinstance(r.rng, (int, np.integer))
        else (None if r.rng is None else jnp.asarray(r.rng, jnp.uint32))
    )
    ref = np.asarray(generate(
        model, params, np.asarray(r.prompt, np.int32)[None],
        max_new_tokens=r.max_new_tokens, temperature=r.temperature,
        top_k=r.top_k, top_p=r.top_p, eos_token=r.eos_token, rng=rng,
    ))[0]
    got = h.tokens
    assert got.shape[0] <= ref.shape[0], (got.shape, ref.shape)
    np.testing.assert_array_equal(got, ref[: got.shape[0]])
    if h.finish_reason == "length":
        assert len(h.new_tokens) == r.max_new_tokens
    if h.finish_reason == "eos":
        assert h.new_tokens[-1] == r.eos_token


# -- host-side key schedule (serving.keys) -------------------------------


def test_split_key_matches_jax():
    for seed in (0, 1, 123456789, -7):
        np.testing.assert_array_equal(
            keylib.key_from_seed(seed), np.asarray(jax.random.PRNGKey(seed))
        )
    key = jax.random.PRNGKey(42)
    for n in (1, 2, 3, 17, 64):
        np.testing.assert_array_equal(
            keylib.split_key(np.asarray(key), n),
            np.asarray(jax.random.split(key, n)),
        )


def test_fold_key_matches_jax():
    key = jax.random.PRNGKey(9)
    for d in (0, 1, 5, 2**31 - 1):
        np.testing.assert_array_equal(
            keylib.fold_key(np.asarray(key), d),
            np.asarray(jax.random.fold_in(key, d)),
        )


def test_request_key_ladder_matches_generate_schedule():
    """Row 0 = first-token key, rows 1.. = the decode-loop split — the
    exact derivation inside generate()'s compiled program."""
    rng = jax.random.PRNGKey(5)
    rng0, rng_loop = jax.random.split(rng)
    for n in (1, 2, 9):
        ladder = keylib.request_key_ladder(np.asarray(rng), n)
        assert ladder.shape == (n, 2)
        np.testing.assert_array_equal(ladder[0], np.asarray(rng0))
        if n > 1:
            np.testing.assert_array_equal(
                ladder[1:], np.asarray(jax.random.split(rng_loop, n - 1))
            )


# -- per-slot sampler vs inference._sample -------------------------------


@pytest.mark.parametrize(
    "temperature,top_k,top_p",
    [
        (0.0, None, None),   # greedy
        (0.7, 5, None),      # sort-free top-k path
        (0.7, VOCAB, None),  # top_k == vocab: keeps everything
        (1.0, None, 0.9),    # nucleus alone (full-sort path)
        (0.8, 8, 0.5),       # both filters compose
        (1.3, None, None),   # plain temperature
    ],
)
def test_sample_slot_matches_reference(temperature, top_k, top_p):
    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(VOCAB).astype(np.float32) * 4)
    for s in range(2):
        key = jax.random.PRNGKey(s)
        got = sample_slot(
            logits, np.asarray(key),
            jnp.float32(temperature),
            jnp.int32(top_k or 0), jnp.float32(top_p or 0.0),
        )
        ref = _sample(logits[None], key, temperature, top_k, top_p)[0]
        assert int(got) == int(ref), (temperature, top_k, top_p, s)


# -- parity oracle: adversarial co-scheduling ----------------------------


def test_parity_greedy_staggered_mixed_lengths(engine, model, params):
    """8 greedy requests over 4 slots, mixed buckets, admitted one per
    tick (staggered joins), different max_new — every stream bitwise."""
    rng = np.random.RandomState(0)
    server = Server(engine, prefills_per_step=1)
    handles = [
        server.submit(Request(
            prompt=_prompt(rng, n), max_new_tokens=m,
        ))
        for n, m in [(3, 6), (7, 9), (12, 4), (16, 10),
                     (4, 12), (9, 3), (14, 7), (5, 5)]
    ]
    server.drain()
    assert all(h.status == "done" for h in handles)
    for h in handles:
        _assert_request_parity(h, model, params)


def test_parity_sampled_churn_with_evictions(engine, model, params):
    """Seeded-sampled requests under the nastiest co-scheduling we can
    stage: staggered joins, a mid-stream cancellation freeing a slot
    that is immediately re-admitted into, mixed greedy/sampled configs.
    Every surviving stream bitwise; the victim's prefix bitwise too.
    And the whole churn triggers ZERO backend compiles."""
    from jax._src import monitoring

    compiles = []
    monitoring.register_event_duration_secs_listener(
        lambda event, duration, **kw: compiles.append(event)
        if "backend_compile" in event else None
    )
    baseline = len(compiles)

    rng = np.random.RandomState(1)
    server = Server(engine, prefills_per_step=2)
    mk = lambda n, m, seed, **kw: server.submit(Request(  # noqa: E731
        prompt=_prompt(rng, n), max_new_tokens=m, rng=seed, **kw
    ))
    wave1 = [
        mk(3, 10, 11, temperature=0.9, top_k=8),
        mk(8, 12, 12, temperature=0.7, top_k=5),
        mk(13, 12, 13),  # greedy neighbour in the same pool
        mk(16, 8, 14, temperature=1.1, top_k=40, top_p=0.9),
    ]
    for _ in range(4):
        server.step()
    victim = wave1[1]
    victim.cancel()  # mid-stream eviction
    wave2 = [
        mk(5, 9, 21, temperature=0.8, top_k=6),   # lands in freed slot
        mk(10, 6, 22, temperature=1.0, top_p=0.8),
    ]
    server.drain()
    # Zero backend compiles across the whole churn — checked BEFORE the
    # parity loop below, whose reference generate() calls legitimately
    # compile new request shapes.
    assert len(compiles) == baseline, compiles[baseline:]
    assert victim.status == "cancelled"
    assert 0 < len(victim.new_tokens) < victim.request.max_new_tokens
    for h in wave1 + wave2:
        _assert_request_parity(h, model, params)


def test_parity_eos_freezes_and_frees_slot(engine, model, params):
    """A request that hits eos mid-stream finishes early (stream ends at
    the eos token) and its slot is reused; neighbours unaffected."""
    rng = np.random.RandomState(2)
    prompt = _prompt(rng, 5)
    ref = np.asarray(generate(model, params, prompt[None],
                              max_new_tokens=12))[0]
    eos = int(ref[5 + 2])  # third greedy token → eos at step 3
    server = Server(engine)
    h_eos = server.submit(Request(
        prompt=prompt, max_new_tokens=12, eos_token=eos,
    ))
    h_other = server.submit(Request(prompt=_prompt(rng, 9),
                                    max_new_tokens=10))
    server.drain()
    assert h_eos.finish_reason == "eos"
    gen = ref[5:]
    first = int(np.argmax(gen == eos))
    assert len(h_eos.new_tokens) == first + 1
    _assert_request_parity(h_eos, model, params)
    _assert_request_parity(h_other, model, params)
    assert engine.occupancy == 0.0


def test_generate_engine_routing_bitwise(engine, model, params):
    """inference.generate(engine=...) — B=1 bitwise for greedy AND
    seeded sampling; B>1 bitwise for greedy (keyless, so per-row
    scheduling cannot matter)."""
    rng = np.random.RandomState(4)
    server = Server(engine)
    p1 = rng.randint(0, VOCAB, size=(1, 6)).astype(np.int32)
    for kw in (
        dict(),
        dict(temperature=0.8, top_k=7, rng=jax.random.PRNGKey(3)),
        dict(temperature=1.0, top_p=0.85, rng=jax.random.PRNGKey(4)),
    ):
        ref = np.asarray(generate(model, params, p1, max_new_tokens=8, **kw))
        got = np.asarray(generate(model, params, p1, max_new_tokens=8,
                                  engine=server, **kw))
        np.testing.assert_array_equal(got, ref)
    pb = rng.randint(0, VOCAB, size=(3, 5)).astype(np.int32)
    ref = np.asarray(generate(model, params, pb, max_new_tokens=6))
    got = np.asarray(generate(model, params, pb, max_new_tokens=6,
                              engine=server))
    np.testing.assert_array_equal(got, ref)


def test_generate_engine_eos_padding(engine, model, params):
    """eos/pad semantics through the engine route match generate's:
    finished rows freeze to pad_token, shapes stay [B, Tp+n]."""
    rng = np.random.RandomState(6)
    p1 = rng.randint(0, VOCAB, size=(1, 4)).astype(np.int32)
    ref = np.asarray(generate(model, params, p1, max_new_tokens=10))
    eos = int(ref[0, 4 + 1])
    server = Server(engine)
    want = np.asarray(generate(
        model, params, p1, max_new_tokens=10, eos_token=eos, pad_token=0,
    ))
    got = np.asarray(generate(
        model, params, p1, max_new_tokens=10, eos_token=eos, pad_token=0,
        engine=server,
    ))
    assert got.shape == want.shape == (1, 14)
    np.testing.assert_array_equal(got, want)
    assert eos in got[0]  # eos actually fired; the tail froze to pad
    np.testing.assert_array_equal(
        got[0, 4 + int(np.argmax(got[0, 4:] == eos)) + 1:], 0
    )


# -- compiled-program budget ---------------------------------------------


def test_compile_count_bound_and_warmup_idempotent(engine):
    """The closed program set: exactly bucket_count + 1 executables, and
    re-warmup adds none."""
    assert engine.compile_count == len(BUCKETS) + 1
    info = engine.warmup()
    assert engine.compile_count == len(BUCKETS) + 1
    assert info["programs"] == float(len(BUCKETS) + 1)


def test_bucket_ladder():
    from distributeddeeplearning_tpu.serving.engine import default_buckets

    assert default_buckets(32) == (16, 32)
    assert default_buckets(100) == (16, 32, 64, 100)
    eng_buckets = BUCKETS
    for plen, want in ((1, 4), (4, 4), (5, 8), (16, 16)):
        b = [b for b in eng_buckets if plen <= b][0]
        assert b == want


def test_request_validation(engine):
    with pytest.raises(ValueError, match="bucket"):
        ReqSpec(np.zeros(17, np.int32), 2).validate(MAX_LEN, BUCKETS[-1])
    with pytest.raises(ValueError, match="cache length"):
        ReqSpec(np.zeros(16, np.int32), 17).validate(MAX_LEN, BUCKETS[-1])
    with pytest.raises(ValueError, match="max_new_tokens"):
        ReqSpec(np.zeros(4, np.int32), 0).validate(MAX_LEN, BUCKETS[-1])
    with pytest.raises(ValueError, match="top_p"):
        ReqSpec(np.zeros(4, np.int32), 2, temperature=1.0,
                top_p=0.0).validate(MAX_LEN, BUCKETS[-1])
    engine.prefill(0, ReqSpec(np.zeros(3, np.int32), 2))
    with pytest.raises(ValueError, match="occupied"):
        engine.prefill(0, ReqSpec(np.zeros(3, np.int32), 2))


def test_top_k_cap_enforced(model, params):
    eng = SlotEngine(
        model, params, num_slots=1, max_len=MAX_LEN, buckets=(8,),
        top_k_cap=4,
    )
    eng.warmup()
    with pytest.raises(ValueError, match="top_k_cap"):
        eng.prefill(0, ReqSpec(
            np.zeros(3, np.int32), 2, temperature=1.0, top_k=8,
        ))
    # with nucleus sampling in play the full-sort path serves any top_k
    tok, _ = eng.prefill(0, ReqSpec(
        np.zeros(3, np.int32), 2, temperature=1.0, top_k=8, top_p=0.9,
        rng=3,
    ))
    assert 0 <= tok < VOCAB
    # top_k >= vocab is "keep everything" — admitted on the capped path
    eng.release(0)
    eng.prefill(0, ReqSpec(
        np.zeros(3, np.int32), 2, temperature=1.0, top_k=VOCAB + 10, rng=3,
    ))
    assert eng.compile_count == 2  # decode + one bucket, still closed
    # ...and the cap rejects at SUBMIT time — the client's call site,
    # never the serving loop's pump thread
    eng.release(0)
    with pytest.raises(ValueError, match="top_k_cap"):
        Server(eng).submit(Request(
            prompt=np.zeros(3, np.int32), max_new_tokens=2,
            temperature=1.0, top_k=8,
        ))


# -- scheduler lifecycle -------------------------------------------------


def test_queue_backpressure(engine):
    server = Server(engine, queue_depth=2)
    reqs = [Request(prompt=np.zeros(3, np.int32), max_new_tokens=2)
            for _ in range(3)]
    server.submit(reqs[0])
    server.submit(reqs[1])
    with pytest.raises(QueueFull):
        server.submit(reqs[2])
    assert server.stats["rejected"] == 1
    server.drain()


def test_deadline_evicts_queued_and_running(engine):
    server = Server(engine)
    # queued request whose deadline passes before admission
    dead = server.submit(Request(
        prompt=np.zeros(3, np.int32), max_new_tokens=4, deadline_ms=0.1,
    ))
    time.sleep(0.01)
    server.step()
    assert dead.status == "deadline" and dead.finish_reason == "deadline"
    assert dead.new_tokens == []
    # running request evicted mid-stream once its deadline expires
    run = server.submit(Request(
        prompt=np.zeros(4, np.int32), max_new_tokens=20, deadline_ms=40.0,
    ))
    server.step()  # admitted + first decode
    assert run.status == "running"
    time.sleep(0.06)
    server.drain()
    assert run.status == "deadline"
    assert 0 < len(run.new_tokens) < 20
    assert engine.occupancy == 0.0
    assert server.stats["deadline"] == 2


def test_cancel_queued_request(engine):
    server = Server(engine)
    h = server.submit(Request(prompt=np.zeros(3, np.int32),
                              max_new_tokens=4))
    h.cancel()
    server.drain()
    assert h.status == "cancelled" and h.new_tokens == []


def test_result_blocks_and_close_rejects(engine):
    server = Server(engine)
    h = server.submit(Request(prompt=np.zeros(3, np.int32),
                              max_new_tokens=3))
    with pytest.raises(TimeoutError):
        h.result(timeout=0)
    server.close()
    assert h.status == "done"
    assert h.result(timeout=0).shape == (6,)
    with pytest.raises(RuntimeError, match="closed"):
        server.submit(Request(prompt=np.zeros(3, np.int32),
                              max_new_tokens=3))


def test_default_deadline_applied(engine):
    server = Server(engine, default_deadline_ms=0.05)
    h = server.submit(Request(prompt=np.zeros(3, np.int32),
                              max_new_tokens=4))
    assert h.request.deadline_ms == 0.05
    time.sleep(0.01)
    server.drain()
    assert h.status == "deadline"


def test_serve_config_from_env():
    cfg = ServeConfig.from_env({
        "SERVE_SLOTS": "16", "SERVE_BUCKETS": "8,32, 64",
        "SERVE_QUEUE_DEPTH": "5", "SERVE_DEADLINE_MS": "1500",
        "SERVE_PREFILLS_PER_STEP": "2", "SERVE_TOP_K_CAP": "256",
    })
    assert cfg.num_slots == 16
    assert cfg.buckets == (8, 32, 64)
    assert cfg.queue_depth == 5
    assert cfg.deadline_ms == 1500.0
    assert cfg.prefills_per_step == 2
    assert cfg.top_k_cap == 256
    dflt = ServeConfig.from_env({})
    assert dflt.num_slots == 8 and dflt.buckets is None
    assert dflt.deadline_ms is None


def test_server_build_from_config(model, params):
    server = Server.build(model, params, ServeConfig(
        num_slots=2, buckets=(8,), queue_depth=3,
    ))
    assert server.engine.num_slots == 2
    assert server.engine.buckets == (8,)
    assert server.queue_depth == 3


def test_obs_instrumentation(engine, tmp_path):
    """The serving loop's spans/counters/gauges land on the bus and the
    report's serving view renders them."""
    from distributeddeeplearning_tpu import obs
    from distributeddeeplearning_tpu.obs.report import (
        load, render, summarize,
    )

    bus = obs.configure(str(tmp_path), run_id="serve-test", proc=0,
                        install_handlers=False)
    try:
        server = Server(engine)
        rng = np.random.RandomState(8)
        hs = [server.submit(Request(prompt=_prompt(rng, n),
                                    max_new_tokens=4))
              for n in (3, 9)]
        server.drain()
        assert all(h.status == "done" for h in hs)
        bus.flush()
    finally:
        obs.reset()
    summary = summarize(load([str(tmp_path)]))
    srv = summary["serving"]
    assert srv is not None
    assert srv["requests_done"] == 2
    assert srv["admitted"] == 2 and srv["completed"] == 2
    assert srv["tokens"] == 8
    assert srv["occupancy_mean"] is not None
    assert srv["ttft"]["count"] == 2
    assert srv["queue_wait"]["count"] == 2
    assert srv["decode_step"]["count"] >= 3
    text = render(summary)
    assert "serving (continuous batching)" in text
    assert "ttft" in text
