"""First coverage for ``scripts/attn_bench.py`` (satellite of the
quantized-decode PR): the bare ``sys.argv`` parsing became argparse
(``--seq-lens``/``--impls``) and the sweep now ends with bench.py's
one-line JSON record — both contracts pinned here on the CPU tier
(tiny T, xla impl; the long-T Pallas sweep is a TPU exercise)."""

import io
import json
import sys

import pytest

sys.path.insert(0, "scripts")

import attn_bench  # noqa: E402


def _run(argv):
    buf = io.StringIO()
    old = sys.stdout
    sys.stdout = buf
    try:
        rc = attn_bench.main(argv)
    finally:
        sys.stdout = old
    lines = [
        ln for ln in buf.getvalue().splitlines() if ln.startswith("{")
    ]
    return rc, buf.getvalue(), (json.loads(lines[-1]) if lines else None)


def test_json_record_and_args():
    rc, text, rec = _run(
        ["--seq-lens", "64,128", "--impls", "xla", "--steps", "1"]
    )
    assert rc == 0
    assert rec["metric"] == "attn_fwd_bwd_ms"
    assert rec["unit"] == "ms" and rec["value"] > 0
    rows = rec["detail"]["rows"]
    assert [r["seq_len"] for r in rows] == [64, 128]
    assert all(r["impl"] == "xla" for r in rows)
    assert all("fwd_ms" in r and "fwd_bwd_ms" in r for r in rows)
    # the human-readable sweep lines still print
    assert "fwd_bwd" in text


def test_xla_skipped_beyond_materialization_limit(monkeypatch):
    # keep the run tiny: lower the cap instead of running a real 8k+
    monkeypatch.setattr(attn_bench, "XLA_MAX_T", 64)
    rc, _text, rec = _run(
        ["--seq-lens", "128", "--impls", "pallas,xla", "--steps", "1"]
    )
    skipped = rec["detail"]["skipped"]
    assert [s["impl"] for s in skipped] == ["xla"]
    assert skipped[0]["reason"] == "xla_oom"
    # but xla alone at the same T still runs (no silent empty sweep)
    rc2, _t2, rec2 = _run(
        ["--seq-lens", "128", "--impls", "xla", "--steps", "1"]
    )
    assert rc2 == 0 and rec2["detail"]["rows"]


def test_bad_args_rejected():
    with pytest.raises(SystemExit):
        _run(["--seq-lens", "", "--impls", "xla"])
