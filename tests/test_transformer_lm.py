"""Decoder-only LM: causality, registry, and real DP train steps.

The long-context tier trained through the same engine as the vision
models — per-token cross-entropy via the generalized loss, causal
attention through ops.dot_product_attention (xla and pallas impls).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokenDataset
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.training import (
    create_train_state,
    make_train_step,
)
from distributeddeeplearning_tpu.training.train_step import (
    cross_entropy_loss,
    replicate_state,
)

VOCAB = 64
T = 16
CFG = TrainConfig(
    model="lm_tiny",
    num_classes=VOCAB,
    batch_size_per_device=2,
    weight_decay=0.0,
    compute_dtype="float32",
)


def _model(impl="xla"):
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T,
        dtype=jnp.float32, attn_impl=impl,
    )


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    rows = rng.randint(0, VOCAB, size=(n, T + 1)).astype(np.int32)
    return rows[:, :-1], rows[:, 1:]


@pytest.fixture(scope="module")
def state_and_model():
    model = _model()
    tx = optax.sgd(0.5)
    state = create_train_state(
        model, CFG, tx, input_shape=(1, T), input_dtype=jnp.int32
    )
    return model, tx, state


def test_registry_and_vocab_plumbing():
    m = get_model("lm_tiny", num_classes=VOCAB, attn_impl="pallas")
    assert isinstance(m, TransformerLM)
    assert m.vocab_size == VOCAB and m.attn_impl == "pallas"


def test_causality(state_and_model):
    """Logits at position t must not depend on tokens > t."""
    model, _, state = state_and_model
    tokens, _ = _batch(n=2, seed=1)
    out1 = model.apply({"params": state.params}, tokens, train=False)
    perturbed = tokens.copy()
    perturbed[:, -1] = (perturbed[:, -1] + 7) % VOCAB  # change last token
    out2 = model.apply({"params": state.params}, perturbed, train=False)
    np.testing.assert_allclose(
        np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5
    )
    assert np.abs(np.asarray(out1[:, -1]) - np.asarray(out2[:, -1])).max() > 1e-4


def test_token_cross_entropy_shape():
    logits = jnp.zeros((2, 3, VOCAB))
    labels = jnp.zeros((2, 3), jnp.int32)
    loss = cross_entropy_loss(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(VOCAB), rtol=1e-5)


def test_lm_dp_train_step_loss_decreases(state_and_model, mesh8):
    model, tx, state = state_and_model
    state = replicate_state(state, mesh8)
    step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    batch = shard_batch(_batch(), mesh8)
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_lm_pallas_matches_xla(state_and_model, mesh8):
    model, tx, state = state_and_model
    tokens, _ = _batch(n=4, seed=2)
    logits_xla = model.apply({"params": state.params}, tokens, train=False)
    logits_fl = _model("pallas").apply(
        {"params": state.params}, tokens, train=False
    )
    np.testing.assert_allclose(
        np.asarray(logits_fl), np.asarray(logits_xla), atol=2e-3
    )


def test_token_dataset_contract():
    ds = SyntheticTokenDataset(
        length=64, global_batch_size=16, seq_len=T, vocab_size=VOCAB,
        num_physical_batches=2,
    )
    assert ds.steps_per_epoch == 4
    n = 0
    for x, y in ds.epoch(0):
        assert x.shape == (16, T) and y.shape == (16, T)
        assert x.dtype == np.int32
        np.testing.assert_array_equal(x[:, 1:], y[:, :-1])  # shifted pair
        n += 1
    assert n == 4
    # per-process disjoint sharding: local batches halve
    d0 = SyntheticTokenDataset(
        length=64, global_batch_size=16, seq_len=T, vocab_size=VOCAB,
        num_physical_batches=2, process_index=0, process_count=2,
    )
    x0, _ = next(iter(d0.epoch(0)))
    assert x0.shape == (8, T)


def test_lm_trains_through_keras_frontend(mesh8):
    """Front-end reachability: Model('lm_tiny').fit(token_data) — the
    engine infers the (1, seq_len) int32 init signature from the dataset."""
    from distributeddeeplearning_tpu.frontends import Model

    cfg = TrainConfig(
        model="lm_tiny",
        num_classes=VOCAB,
        batch_size_per_device=2,
        weight_decay=0.0,
        compute_dtype="float32",
    )
    data = SyntheticTokenDataset(
        length=32, global_batch_size=16, seq_len=T, vocab_size=VOCAB,
        num_physical_batches=2,
    )
    m = Model(_model(), cfg)
    m.compile()
    result = m.fit(data, epochs=1)
    assert np.isfinite(result.history[-1]["loss"])
    assert int(m.state.step) == 2  # 32/(2*8)
