"""ENGINE=pp / ENGINE=sp as first-class members of the one-engine
contract (SURVEY §1 env-var boundary, §7 "3 API styles over one
runtime"): reachable from ``loop.fit`` and the front-ends with
eval, callbacks, and checkpoint/resume — not library-only paths.
"""

import os

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.synthetic import SyntheticTokenDataset
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.training import loop

VOCAB, T = 64, 16


def _cfg(engine, **kw):
    base = dict(
        engine=engine,
        model="lm_tiny",
        num_classes=VOCAB,
        batch_size_per_device=2,
        fake_data_length=64,
        epochs=1,
        compute_dtype="float32",
        weight_decay=0.0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _model():
    return get_model("lm_tiny", num_classes=VOCAB, dtype="float32", max_seq_len=T)


def _data(cfg, length=None, seed=0):
    return SyntheticTokenDataset(
        length=length or cfg.fake_data_length,
        global_batch_size=cfg.global_batch_size,
        seq_len=T,
        vocab_size=VOCAB,
        seed=seed,
    )


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_engine_pp_fit_trains_and_evals(devices, schedule):
    cfg = _cfg(
        "pp", mesh_axes=("data", "pipe"), mesh_shape=(2, 4),
        pp_microbatches=2, pp_schedule=schedule, validation=True,
    )
    assert cfg.global_batch_size == 4  # 2 per device x 2-wide data axis
    res = loop.fit(
        _model(), cfg, _data(cfg), eval_data=_data(cfg, length=32, seed=1),
        add_default_logger=False,
    )
    assert int(jax.device_get(res.state.step)) == _data(cfg).steps_per_epoch
    assert np.isfinite(res.history[-1]["loss"])
    assert np.isfinite(res.history[-1]["val_loss"])
    # the state really is stage-partitioned
    leaf = jax.tree.leaves(res.state.params["stages"])[0]
    assert leaf.shape[0] == 4 and tuple(leaf.sharding.spec)[:1] == ("pipe",)


def test_engine_sp_fit_trains_and_evals(devices):
    cfg = _cfg(
        "sp", mesh_axes=("data", "seq"), mesh_shape=(2, 4), validation=True
    )
    assert cfg.global_batch_size == 4
    res = loop.fit(
        _model(), cfg, _data(cfg), eval_data=_data(cfg, length=32, seed=1),
        add_default_logger=False,
    )
    assert np.isfinite(res.history[-1]["loss"])
    assert np.isfinite(res.history[-1]["val_loss"])


def test_engine_pp_checkpoint_resume(devices, tmp_path):
    cfg = _cfg(
        "pp", mesh_axes=("data", "pipe"), mesh_shape=(2, 4),
        pp_microbatches=2, epochs=1, model_dir=str(tmp_path),
    )
    data = _data(cfg)
    res1 = loop.fit(_model(), cfg, data, add_default_logger=False)
    # Second fit with epochs=2 resumes from the saved epoch-0 checkpoint:
    # only one more epoch of steps runs, on the restored sharded state.
    res2 = loop.fit(
        _model(), cfg.replace(epochs=2), data, add_default_logger=False
    )
    assert int(jax.device_get(res2.state.step)) == 2 * data.steps_per_epoch
    assert len(res2.history) == 1  # epoch 0 skipped via resume


def test_engine_sp_matches_dp_loss_curve(devices):
    """SP over (1, 8) must reproduce plain DP single-batch training: the
    strategies differ only in layout, not math (ring == full attention)."""
    data_kw = dict(length=32, seq_len=T, vocab_size=VOCAB, seed=0)
    sp_cfg = _cfg(
        "sp", mesh_axes=("data", "seq"), mesh_shape=(1, 8),
        scale_lr_by_world_size=False,
    )
    sp_data = SyntheticTokenDataset(global_batch_size=4, **data_kw)
    sp_res = loop.fit(_model(), sp_cfg, sp_data, add_default_logger=False)

    dp_cfg = _cfg(
        "dp", batch_size_per_device=1, scale_lr_by_world_size=False
    )
    # match global batch exactly: 4 rows over the 8-device data mesh is
    # not expressible; use a 4-device data mesh instead.
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh

    dp_mesh = create_mesh(devices=jax.devices()[:4], axes=("data",))
    dp_data = SyntheticTokenDataset(global_batch_size=4, **data_kw)
    dp_res = loop.fit(
        _model(), dp_cfg, dp_data, mesh=dp_mesh, add_default_logger=False
    )
    np.testing.assert_allclose(
        sp_res.history[-1]["loss"], dp_res.history[-1]["loss"],
        rtol=2e-4,
    )


def test_engine_pp_explicit_frontend(devices):
    """The lm_synthetic_tpu example path: explicit.setup under ENGINE=pp."""
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.frontends import explicit

    cfg = _cfg(
        "pp", mesh_axes=("data", "pipe"), mesh_shape=(2, 4), pp_microbatches=2
    )
    data = _data(cfg)
    pieces, state = explicit.setup(
        _model(), cfg, steps_per_epoch=data.steps_per_epoch,
        input_shape=(1, T), input_dtype=jnp.int32,
    )
    state = explicit.train_epoch(pieces, state, data, 0, log_every=0)
    assert int(jax.device_get(state.step)) == data.steps_per_epoch
    metrics = explicit.validate(pieces, state, _data(cfg, length=32, seed=1))
    # token-model eval counts tokens (32 rows x T), like the dp engine
    assert np.isfinite(metrics["loss"]) and metrics["samples"] == 32 * T


def test_engine_sp_keras_frontend(devices):
    from distributeddeeplearning_tpu.frontends.keras_style import Model

    cfg = _cfg("sp", mesh_axes=("data", "seq"), mesh_shape=(2, 4))
    m = Model(_model(), config=cfg).compile(optimizer="sgd")
    result = m.fit(_data(cfg), epochs=1)
    assert np.isfinite(result.history[-1]["loss"])


def test_resolve_engine_validation(devices):
    from distributeddeeplearning_tpu.training.loop import resolve_engine

    # pp without a pipe axis in an explicit mesh
    with pytest.raises(ValueError, match="pipe"):
        resolve_engine(_cfg("pp", mesh_axes=("data", "model"), mesh_shape=(2, 4)))
    with pytest.raises(ValueError, match="seq"):
        resolve_engine(_cfg("sp", mesh_axes=("data",), mesh_shape=(8,)))
    with pytest.raises(ValueError, match="PP_STAGES"):
        resolve_engine(
            _cfg("pp", mesh_axes=("data", "pipe"), mesh_shape=(2, 4), pp_stages=2)
        )
    with pytest.raises(ValueError, match="PP_SCHEDULE"):
        resolve_engine(_cfg("pp", pp_schedule="interleaved"))
    # engine-default meshes when only ENGINE is set
    engine, mesh = resolve_engine(_cfg("pp", pp_stages=4))
    assert engine == "pp" and mesh.shape == {"data": 2, "pipe": 4}
    engine, mesh = resolve_engine(_cfg("sp"))
    assert engine == "sp" and mesh.shape == {"data": 1, "seq": 8}


def test_pp_env_contract(devices):
    env = {
        "ENGINE": "pp",
        "PP_STAGES": "4",
        "PP_MICROBATCHES": "8",
        "PP_SCHEDULE": "1f1b",
        "MESH_AXES": "data,pipe",
        "MESH_SHAPE": "2,4",
    }
    cfg = TrainConfig.from_env(env)
    assert cfg.engine == "pp" and cfg.pp_stages == 4
    assert cfg.pp_microbatches == 8 and cfg.pp_schedule == "1f1b"
    assert cfg.data_parallel_width == 2
    sp = TrainConfig.from_env({"ENGINE": "sp", "MESH_AXES": "data,seq",
                               "MESH_SHAPE": "4,2"})
    assert sp.data_parallel_width == 4


def test_adapt_model_errors(devices):
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.engines import adapt_model

    mesh = create_mesh(axes=("data", "pipe"), shape=(2, 4))
    vision = get_model("resnet18", num_classes=10)
    with pytest.raises(ValueError, match="LM family"):
        adapt_model(vision, "pp", mesh, _cfg("pp"))
    with pytest.raises(ValueError, match="attn_impl"):
        adapt_model(vision, "sp", mesh, _cfg("sp"))
    moe = get_model("lm_moe_tiny", num_classes=VOCAB, max_seq_len=T)
    with pytest.raises(ValueError, match="dense"):
        adapt_model(moe, "pp", mesh, _cfg("pp"))
    # sp adaptation rebuilds the model with ring attention
    adapted = adapt_model(_model(), "sp", mesh, _cfg("sp"))
    assert adapted.attn_impl == "ring" and adapted.seq_axis == "seq"
