"""Oracles for the sync-free hot loop (ISSUE 1).

Three invariants, all CPU-tier provable:

1. **True epoch means, bit-for-bit.** The loop's epoch logs equal a
   synchronous reference loop's host-side f32 running mean of per-step
   metrics — exactly, in f32 — because the on-device accumulator does
   the identical f32 adds in the identical order.
2. **≤ 1 host materialisation per epoch.** Counted by the hostsync
   accountant while additionally patching ``jax.device_get`` itself
   (``hostsync.track``), so a stray sync anywhere inside ``fit`` —
   callbacks, staging, checkpointing — would be caught.
3. **Warm-cache warmup skips recompilation.** With the persistent
   compilation cache enabled, a second AOT warmup of a fresh engine
   observes cache HITS (and writes no new entries for the same program).

Plus: the accumulating step variant leaves training math untouched
(state bit-identical to the vanilla step) under every engine.
"""

import os

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import prefetch_to_device
from distributeddeeplearning_tpu.data.synthetic import (
    SyntheticImageDataset,
    SyntheticTokenDataset,
)
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.training import loop
from distributeddeeplearning_tpu.training.engines import build_engine
from distributeddeeplearning_tpu.training.metrics import (
    METRIC_KEYS,
    finalize_accumulator,
    init_accumulator,
)
from distributeddeeplearning_tpu.training.optimizer import create_optimizer
from distributeddeeplearning_tpu.utils import hostsync

VOCAB, T = 64, 16


def _cfg(**kw):
    base = dict(
        model="resnet18",
        num_classes=8,
        image_size=16,
        batch_size_per_device=2,
        fake_data_length=48,
        epochs=2,
        compute_dtype="float32",
        log_every_steps=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _image_data(cfg, seed=0):
    return SyntheticImageDataset(
        length=cfg.fake_data_length,
        global_batch_size=cfg.global_batch_size,
        image_size=cfg.image_size,
        num_classes=cfg.num_classes,
        seed=seed,
    )


def _token_cfg(engine, **kw):
    base = dict(
        engine=engine,
        model="lm_tiny",
        num_classes=VOCAB,
        batch_size_per_device=2,
        fake_data_length=32,
        epochs=1,
        compute_dtype="float32",
        weight_decay=0.0,
        log_every_steps=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _token_data(cfg, seed=0):
    return SyntheticTokenDataset(
        length=cfg.fake_data_length,
        global_batch_size=cfg.global_batch_size,
        seq_len=T,
        vocab_size=VOCAB,
        seed=seed,
    )


def _build(model_name, cfg, data, mesh):
    from distributeddeeplearning_tpu.parallel.mesh import dp_size

    tx, _ = create_optimizer(
        cfg, data.steps_per_epoch, world_size=dp_size(mesh)
    )
    model = get_model(
        model_name,
        num_classes=cfg.num_classes,
        dtype=cfg.compute_dtype,
        **({"max_seq_len": T} if model_name.startswith("lm_") else {}),
    )
    from distributeddeeplearning_tpu.training.loop import _init_spec

    shape, dtype = _init_spec(data)
    return build_engine(
        model, cfg, tx, mesh, input_shape=shape, input_dtype=dtype
    )


def test_epoch_means_match_synchronous_reference_bitwise(mesh8):
    """(1): fit's epoch logs == host-side f32 running means of the
    per-step metrics a synchronous (device_get-every-step) loop sees."""
    cfg = _cfg()
    model = get_model("resnet18", num_classes=8, dtype="float32")
    res = loop.fit(
        model, cfg, _image_data(cfg), mesh=mesh8, add_default_logger=False
    )

    # Reference: identical engine from the identical seed, stepped with
    # the plain (non-accumulating) step, materialising EVERY step.
    eng = _build("resnet18", cfg, _image_data(cfg), mesh8)
    state = eng.state
    for epoch in range(cfg.epochs):
        sums = {k: np.float32(0.0) for k in METRIC_KEYS}
        steps = 0
        for batch in prefetch_to_device(
            _image_data(cfg).epoch(epoch), mesh8, size=0
        ):
            state, metrics = eng.train_step(state, batch)
            host = jax.device_get(metrics)  # the sync fit no longer does
            for k in sums:
                sums[k] = np.float32(sums[k] + np.float32(host[k]))
            steps += 1
        for k in sums:
            want = np.float32(sums[k] / np.float32(steps))
            got = np.float32(res.history[epoch][k])
            assert got == want, (epoch, k, got.tobytes(), want.tobytes())


def test_loop_performs_at_most_one_sync_per_epoch(mesh8):
    """(2): the whole fit — staging, callbacks, epoch summary — crosses
    device→host exactly once per epoch."""
    cfg = _cfg(epochs=3)
    model = get_model("resnet18", num_classes=8, dtype="float32")
    hostsync.accountant().reset()
    with hostsync.track():
        res = loop.fit(
            model, cfg, _image_data(cfg), mesh=mesh8,
            add_default_logger=False,
        )
    acct = hostsync.accountant()
    assert acct.count == cfg.epochs, acct.by_label
    assert acct.by_label.get("epoch_metrics") == cfg.epochs
    assert res.perf["host_sync_count"] == cfg.epochs
    # ...and the loop really used the accumulator: true means, not the
    # last step's values, reached history (epoch_images sanity too).
    assert res.history[0]["epoch_images"] == cfg.fake_data_length // 16 * 16


@pytest.mark.parametrize("engine", ["dp", "pjit", "sp", "pp"])
def test_accumulating_step_is_math_neutral(engine, mesh8):
    """The acc-threading variant must not perturb training: same seed +
    same batches => bit-identical params, and the accumulator's means
    equal the f32 mean of the per-step metrics it saw."""
    from distributeddeeplearning_tpu.training.loop import resolve_engine

    kw = {}
    if engine == "pp":
        kw = dict(
            mesh_axes=("data", "pipe"), mesh_shape=(2, 4), pp_microbatches=2
        )
    elif engine == "sp":
        kw = dict(mesh_axes=("data", "seq"), mesh_shape=(2, 4))
    cfg = _token_cfg(engine, **kw)
    _, mesh = resolve_engine(cfg)
    data = _token_data(cfg)

    eng_a = _build("lm_tiny", cfg, data, mesh)
    eng_b = _build("lm_tiny", cfg, data, mesh)
    state_a, state_b = eng_a.state, eng_b.state
    acc = init_accumulator(mesh)
    per_step = []
    for batch in prefetch_to_device(
        data.epoch(0), mesh, size=0, sharding=eng_a.batch_sharding
    ):
        state_a, m_a = eng_a.train_step(state_a, batch)
        state_b, m_b, acc = eng_b.train_step(state_b, batch, acc)
        per_step.append(jax.device_get(m_b))
        np.testing.assert_array_equal(
            jax.device_get(m_a["loss"]), jax.device_get(m_b["loss"])
        )
    for la, lb in zip(
        jax.tree.leaves(jax.device_get(state_a.params)),
        jax.tree.leaves(jax.device_get(state_b.params)),
    ):
        np.testing.assert_array_equal(la, lb)
    means = jax.device_get(finalize_accumulator(acc))
    for k in METRIC_KEYS:
        run = np.float32(0.0)
        for m in per_step:
            run = np.float32(run + np.float32(m[k]))
        want = np.float32(run / np.float32(len(per_step)))
        assert np.float32(means[k]) == want, (k, means[k], want)


def test_sync_invariant_holds_with_event_bus_enabled(mesh8, tmp_path):
    """ISSUE 2 hard constraint: with the event bus WRITING (OBS_DIR
    live), instrumentation adds zero host syncs — the ≤1-per-epoch
    invariant holds under hostsync.track(), and the captured events
    prove the bus saw the whole run from host-side floats only."""
    import json

    from distributeddeeplearning_tpu import obs

    cfg = _token_cfg("dp", epochs=2)
    bus = obs.configure(str(tmp_path / "run"))
    try:
        hostsync.accountant().reset()
        with hostsync.track():
            res = loop.fit(
                get_model("lm_tiny", num_classes=VOCAB, dtype="float32",
                          max_seq_len=T),
                cfg,
                _token_data(cfg),
                mesh=mesh8,
                add_default_logger=False,
            )
        acct = hostsync.accountant()
        assert acct.count == cfg.epochs, acct.by_label
        assert acct.by_label.get("epoch_metrics") == cfg.epochs
        assert res.perf["host_sync_count"] == cfg.epochs
        # The bus captured the run: per-step spans, per-epoch spans, and
        # exactly the epoch-boundary materialisations as sync counters.
        lines = [json.loads(ln) for ln in open(bus.path)]
        steps = [r for r in lines
                 if r.get("kind") == "span" and r["name"] == "step"]
        epochs = [r for r in lines
                  if r.get("kind") == "span" and r["name"] == "epoch"]
        syncs = [r for r in lines
                 if r.get("kind") == "counter" and r["name"] == "host_sync"]
        assert len(epochs) == cfg.epochs
        assert len(steps) == cfg.epochs * _token_data(cfg).steps_per_epoch
        assert sum(r["value"] for r in syncs) == cfg.epochs
        assert {r["labels"]["label"] for r in syncs} == {"epoch_metrics"}
    finally:
        obs.reset()


def test_warm_persistent_cache_skips_recompilation(mesh8, tmp_path):
    """(3): second AOT warmup against a warm on-disk cache observes
    cache hits; the executables really landed on disk the first time."""
    from distributeddeeplearning_tpu.training import warmup as wu

    cache_dir = str(tmp_path / "xla-cache")
    wu.enable_persistent_cache(cache_dir)
    try:
        cfg = _token_cfg("dp", aot_warmup=True)
        data = _token_data(cfg)
        eng = _build("lm_tiny", cfg, data, mesh8)
        batch = next(
            iter(prefetch_to_device(data.epoch(0), mesh8, size=0))
        )
        acc = init_accumulator(mesh8)

        info1 = eng.warmup(batch, acc=acc)
        assert info1["train_compile_sec"] > 0
        assert info1["compile_sec"] > 0
        n_entries = len(os.listdir(cache_dir))
        assert n_entries > 0  # the compile was persisted

        # Fresh engine (fresh jit wrappers) + cleared in-memory caches:
        # the only way the second compile can be cheap is the disk cache.
        jax.clear_caches()
        eng2 = _build("lm_tiny", cfg, data, mesh8)
        info2 = eng2.warmup(batch, acc=acc)
        assert info2["persistent_cache_hits"] > 0, info2
        assert info2["persistent_cache_misses"] == 0, info2
        # the warm pass may lazily persist small helper programs that
        # were only in-memory before, but never re-writes the step
        assert len(os.listdir(cache_dir)) >= n_entries
    finally:
        wu.enable_persistent_cache(None)


def test_fit_aot_warmup_reports_compile_sec(mesh8):
    """AOT_WARMUP=1 end-to-end: fit compiles up front and surfaces
    compile_sec (+ FLOPs when the backend reports them) in perf."""
    cfg = _token_cfg("dp", aot_warmup=True)
    res = loop.fit(
        get_model("lm_tiny", num_classes=VOCAB, dtype="float32",
                  max_seq_len=T),
        cfg,
        _token_data(cfg),
        mesh=mesh8,
        add_default_logger=False,
    )
    assert res.perf["train_compile_sec"] > 0
    assert res.perf["compile_sec"] > 0
    assert res.perf["host_sync_count"] == cfg.epochs
    assert np.isfinite(res.history[-1]["loss"])


def test_config_env_contract():
    cfg = TrainConfig.from_env(
        {"COMPILATION_CACHE_DIR": "/tmp/xla", "AOT_WARMUP": "1"}
    )
    assert cfg.compilation_cache_dir == "/tmp/xla"
    assert cfg.aot_warmup is True
    # empty dir = explicitly off (recertify's opt-out contract)
    assert (
        TrainConfig.from_env({"COMPILATION_CACHE_DIR": ""}).compilation_cache_dir
        is None
    )
