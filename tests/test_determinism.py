"""Determinism audit — the framework's answer to SURVEY §5's "race
detection: absent" row.

On TPU the classic data-race detectors don't apply; the meaningful
property is *bitwise run-to-run reproducibility* of the compiled step:
same seed + same data ⇒ identical parameters, across process restarts
and across engines. A nondeterministic reduction, an unseeded rng, or
host-order-dependent batch assembly breaks these assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.data.synthetic import SyntheticImageDataset
from distributeddeeplearning_tpu.models.resnet import ResNet
from distributeddeeplearning_tpu.training import create_train_state, make_train_step
from distributeddeeplearning_tpu.training.train_step import replicate_state

CFG = TrainConfig(num_classes=8, image_size=16, batch_size_per_device=2,
                  compute_dtype="float32")


def _run_twice(build_and_train):
    a = build_and_train()
    b = build_and_train()
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_dp_step_bitwise_reproducible(mesh8):
    """Full rebuild (init + compile + 3 steps) twice ⇒ bitwise-identical
    parameters. Covers seeded init, dropout rng derivation, and the
    pmean reduction order."""
    rng = np.random.RandomState(0)
    batch_np = (
        rng.randn(16, 16, 16, 3).astype(np.float32),
        rng.randint(0, 8, size=(16,)).astype(np.int32),
    )

    def build_and_train():
        model = ResNet(depth=18, num_classes=8, dtype=jnp.float32)
        tx = optax.sgd(0.1, momentum=0.9)
        state = replicate_state(create_train_state(model, CFG, tx), mesh8)
        step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
        batch = shard_batch(batch_np, mesh8)
        for _ in range(3):
            state, _ = step(state, batch)
        return jax.device_get(state.params)

    _run_twice(build_and_train)


def test_stochastic_model_reproducible(mesh8):
    """Dropout draws from a derived (seed, step, device) key — two
    identical runs of a stochastic model must still agree bitwise."""
    from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM

    vocab, t = 32, 8
    rng = np.random.RandomState(1)
    rows = rng.randint(0, vocab, size=(16, t + 1)).astype(np.int32)
    cfg = CFG.replace(num_classes=vocab)

    def build_and_train():
        model = TransformerLM(
            variant="tiny", vocab_size=vocab, max_seq_len=t,
            dtype=jnp.float32, dropout=0.1,
        )
        tx = optax.sgd(0.1)
        state = replicate_state(
            create_train_state(model, cfg, tx, input_shape=(1, t),
                               input_dtype=jnp.int32),
            mesh8,
        )
        step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
        batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh8)
        for _ in range(2):
            state, _ = step(state, batch)
        return jax.device_get(state.params)

    _run_twice(build_and_train)


def test_dataset_stream_reproducible():
    """The synthetic pipeline (incl. the native counter-mode fill) is a
    pure function of (seed, epoch, process): two constructions yield
    byte-identical batches, different seeds differ."""
    def batches(seed):
        ds = SyntheticImageDataset(
            length=64, global_batch_size=16, image_size=8, num_classes=4,
            num_physical_batches=2, seed=seed,
        )
        return [b for b in ds.epoch(0)] + [b for b in ds.epoch(1)]

    for (xa, ya), (xb, yb) in zip(batches(42), batches(42)):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
    diff = any(
        not np.array_equal(a[0], b[0])
        for a, b in zip(batches(42), batches(43))
    )
    assert diff


def test_pp_and_sp_engines_bitwise_reproducible(mesh8):
    """The round-3 engine-contract strategies inherit the determinism
    guarantee: full rebuild (init + compile + 2 steps) of ENGINE=pp
    (1F1B) and ENGINE=sp twice each ⇒ bitwise-identical parameters.
    Covers the 1F1B per-tick vjp/ring-buffer schedule and the ring-
    attention rotation."""
    from distributeddeeplearning_tpu.data.synthetic import SyntheticTokenDataset
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    for engine, extra in (
        ("pp", dict(mesh_axes=("data", "pipe"), mesh_shape=(2, 4),
                    pp_microbatches=2, pp_schedule="1f1b")),
        ("sp", dict(mesh_axes=("data", "seq"), mesh_shape=(2, 4))),
    ):
        cfg = TrainConfig(
            engine=engine, model="lm_tiny", num_classes=32,
            batch_size_per_device=2, fake_data_length=16, epochs=1,
            compute_dtype="float32", weight_decay=0.0, **extra,
        )

        def build_and_train():
            data = SyntheticTokenDataset(
                length=16, global_batch_size=cfg.global_batch_size,
                seq_len=8, vocab_size=32, seed=0,
            )
            res = loop.fit(
                get_model("lm_tiny", num_classes=32, dtype="float32",
                          max_seq_len=8),
                cfg, data, add_default_logger=False,
            )
            return jax.device_get(res.state.params)

        _run_twice(build_and_train)
