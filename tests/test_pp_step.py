"""Pipeline-parallel tests (models/pipeline_lm.py + training/pp_step.py).

The oracle is ``PipelineLM.apply_reference`` — the same math run
sequentially on one device. The pipelined schedule (GPipe fill-drain,
ppermute hops, masked ramp ticks) must reproduce its loss and its
parameter update exactly; if a masked garbage tick leaked into the loss
or a psum double-counted a replicated grad, these comparisons break.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.models.pipeline_lm import PipelineLM
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.training.pp_step import (
    create_pp_state,
    make_pp_eval_step,
    make_pp_train_step,
    pp_state_specs,
)
from distributeddeeplearning_tpu.training.train_step import cross_entropy_loss

VOCAB, T = 32, 8
CFG = TrainConfig(num_classes=VOCAB, batch_size_per_device=1,
                  weight_decay=0.0, compute_dtype="float32")


def _pl(stages=4, layers=4, dropout=0.0):
    return PipelineLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T,
        num_stages=stages, n_layers=layers, dtype=jnp.float32,
        dropout=dropout,
    )


def _rows(n, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, VOCAB, size=(n, T + 1)).astype(np.int32)


@pytest.fixture(scope="module")
def pp_mesh(devices):
    return create_mesh(axes=("data", "pipe"), shape=(2, 4))


def _put_batch(rows, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = NamedSharding(mesh, P("data"))
    return (
        jax.device_put(rows[:, :-1], spec),
        jax.device_put(rows[:, 1:], spec),
    )


def test_state_sharded_per_stage(pp_mesh):
    pl = _pl()
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_pp_state(pl, CFG, tx, pp_mesh, T)
    leaf = jax.tree.leaves(state.params["stages"])[0]
    assert leaf.shape[0] == 4  # stacked stage axis
    assert tuple(leaf.sharding.spec)[:1] == ("pipe",)
    # optimizer momentum mirrors the stage sharding
    stage_moms = [
        l for l in jax.tree.leaves(state.opt_state)
        if getattr(l, "shape", ())[:1] == (4,)
    ]
    assert stage_moms
    for m in stage_moms:
        assert tuple(m.sharding.spec)[:1] == ("pipe",)
    emb = state.params["embed"]["tok_embed"]
    assert all(p is None for p in tuple(emb.sharding.spec))


def test_pp_matches_sequential_reference(pp_mesh):
    """One PP×DP step == the single-device update, exactly (f32)."""
    pl = _pl()
    tx = optax.sgd(0.1, momentum=0.9)
    rows = _rows(8)
    tokens, labels = rows[:, :-1], rows[:, 1:]

    state = create_pp_state(pl, CFG, tx, pp_mesh, T)
    host_params = jax.device_get(state.params)
    step = make_pp_train_step(pl, tx, pp_mesh, CFG, num_microbatches=2,
                              donate_state=False)
    new_state, metrics = step(state, _put_batch(rows, pp_mesh))

    def ref_loss(params):
        logits = pl.apply_reference(params, jnp.asarray(tokens), train=True)
        return cross_entropy_loss(logits, jnp.asarray(labels))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(host_params)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(loss_ref), rtol=1e-5
    )
    updates, _ = tx.update(grads_ref, tx.init(host_params), host_params)
    ref_new = jax.tree.map(lambda p, u: p + u, host_params, updates)
    got = jax.device_get(new_state.params)
    for (pa, a), (pb, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(ref_new),
               key=lambda kv: str(kv[0])),
        sorted(jax.tree_util.tree_leaves_with_path(got),
               key=lambda kv: str(kv[0])),
    ):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, err_msg=str(pa)
        )


def test_pp_loss_decreases(pp_mesh):
    pl = _pl()
    tx = optax.sgd(0.05)
    state = create_pp_state(pl, CFG, tx, pp_mesh, T)
    step = make_pp_train_step(pl, tx, pp_mesh, CFG, num_microbatches=4,
                              donate_state=False)
    batch = _put_batch(_rows(8), pp_mesh)
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    assert int(jax.device_get(state.step)) == 5


def test_pp_pipe_only_mesh(devices):
    """Pure pipeline (no data axis): 8 stages across all devices."""
    mesh = create_mesh(axes=("pipe",), shape=(8,))
    pl = _pl(stages=8, layers=8)
    tx = optax.sgd(0.1)
    state = create_pp_state(pl, CFG, tx, mesh, T)
    step = make_pp_train_step(pl, tx, mesh, CFG, num_microbatches=2,
                              donate_state=False)
    from jax.sharding import NamedSharding, PartitionSpec as P

    rows = _rows(4, seed=1)
    rep = NamedSharding(mesh, P())
    batch = (jax.device_put(rows[:, :-1], rep), jax.device_put(rows[:, 1:], rep))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    state, m2 = step(state, batch)
    assert np.isfinite(float(m2["loss"]))
    assert int(jax.device_get(state.step)) == 2


def test_pp_eval_exact_coverage(pp_mesh):
    pl = _pl()
    tx = optax.sgd(0.1)
    state = create_pp_state(pl, CFG, tx, pp_mesh, T)
    eval_step = make_pp_eval_step(pl, pp_mesh)
    rows = _rows(8, seed=2)
    tokens, labels = rows[:, :-1], rows[:, 1:]
    m = eval_step(state, _put_batch(rows, pp_mesh))
    assert float(m["count"]) == 8 * T  # per-token counting
    assert np.isfinite(float(m["loss"]))
    # eval logits == sequential reference logits (loss comparison)
    ref_logits = pl.apply_reference(
        jax.device_get(state.params), jnp.asarray(tokens), train=False
    )
    from distributeddeeplearning_tpu.training.train_step import eval_metrics_fn

    sums = eval_metrics_fn(
        ref_logits, jnp.asarray(labels), jnp.ones((8,), jnp.float32)
    )
    np.testing.assert_allclose(
        float(m["loss"]), float(sums["loss"]) / float(sums["count"]), rtol=1e-5
    )


def test_pp_validation_errors(pp_mesh):
    pl = _pl(stages=3, layers=4)  # 4 % 3 != 0
    with pytest.raises(ValueError, match="not divisible"):
        pl.layers_per_stage
    pl4 = _pl()
    tx = optax.sgd(0.1)
    mesh_nopipe = create_mesh(devices=jax.devices())
    with pytest.raises(ValueError, match="pipe"):
        make_pp_train_step(pl4, tx, mesh_nopipe, CFG)


def test_pp_1f1b_matches_sequential_reference(pp_mesh):
    """The 1F1B schedule (hand-scheduled per-tick vjp, 2S-slot input ring
    buffer) computes the identical update to the sequential oracle — and
    therefore to the GPipe schedule."""
    pl = _pl()
    tx = optax.sgd(0.1, momentum=0.9)
    rows = _rows(8)
    tokens, labels = rows[:, :-1], rows[:, 1:]

    state = create_pp_state(pl, CFG, tx, pp_mesh, T)
    host_params = jax.device_get(state.params)
    step = make_pp_train_step(pl, tx, pp_mesh, CFG, num_microbatches=2,
                              schedule="1f1b", donate_state=False)
    new_state, metrics = step(state, _put_batch(rows, pp_mesh))

    def ref_loss(params):
        logits = pl.apply_reference(params, jnp.asarray(tokens), train=True)
        return cross_entropy_loss(logits, jnp.asarray(labels))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(host_params)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(loss_ref), rtol=1e-5
    )
    updates, _ = tx.update(grads_ref, tx.init(host_params), host_params)
    ref_new = jax.tree.map(lambda p, u: p + u, host_params, updates)
    got = jax.device_get(new_state.params)
    for (pa, a), (pb, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(ref_new),
               key=lambda kv: str(kv[0])),
        sorted(jax.tree_util.tree_leaves_with_path(got),
               key=lambda kv: str(kv[0])),
    ):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, err_msg=str(pa)
        )


def test_pp_1f1b_with_weight_decay_and_more_microbatches(pp_mesh):
    """L2 closed-form grads + M > S scheduling (steady-state 1F1B)."""
    cfg = CFG.replace(weight_decay=5e-4)
    pl = _pl()
    tx = optax.sgd(0.1)
    rows = _rows(16, seed=3)
    tokens, labels = rows[:, :-1], rows[:, 1:]
    state = create_pp_state(pl, cfg, tx, pp_mesh, T)
    host_params = jax.device_get(state.params)
    step = make_pp_train_step(pl, tx, pp_mesh, cfg, num_microbatches=8,
                              schedule="1f1b", donate_state=False)
    new_state, metrics = step(state, _put_batch(rows, pp_mesh))

    from distributeddeeplearning_tpu.training.train_step import (
        l2_kernel_penalty,
    )

    def ref_loss(params):
        logits = pl.apply_reference(params, jnp.asarray(tokens), train=True)
        return cross_entropy_loss(logits, jnp.asarray(labels)) + (
            l2_kernel_penalty(params, cfg.weight_decay)
        )

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(host_params)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(loss_ref), rtol=1e-5
    )
    updates, _ = tx.update(grads_ref, tx.init(host_params), host_params)
    ref_new = jax.tree.map(lambda p, u: p + u, host_params, updates)
    got = jax.device_get(new_state.params)
    for (pa, a), (pb, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(ref_new),
               key=lambda kv: str(kv[0])),
        sorted(jax.tree_util.tree_leaves_with_path(got),
               key=lambda kv: str(kv[0])),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, err_msg=str(pa)
        )


def test_pp_schedules_agree_with_dropout(pp_mesh):
    """ADVICE r3: with dropout > 0 both schedules must draw the SAME
    noise — GPipe folds the per-device rng by microbatch index exactly
    like 1F1B — so the two schedules stay loss- and update-equivalent
    stochastically, not just in expectation."""
    pl = _pl(dropout=0.3)
    tx = optax.sgd(0.1)
    rows = _rows(8, seed=5)
    state = create_pp_state(pl, CFG, tx, pp_mesh, T)
    batch = _put_batch(rows, pp_mesh)
    outs = {}
    for schedule in ("gpipe", "1f1b"):
        step = make_pp_train_step(pl, tx, pp_mesh, CFG, num_microbatches=2,
                                  schedule=schedule, donate_state=False)
        new_state, metrics = step(state, batch)
        outs[schedule] = (float(metrics["loss"]),
                          jax.device_get(new_state.params))
    np.testing.assert_allclose(outs["gpipe"][0], outs["1f1b"][0], rtol=1e-5)
    for a, b in zip(jax.tree.leaves(outs["gpipe"][1]),
                    jax.tree.leaves(outs["1f1b"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
