"""End-to-end train-step tests on the 8-device CPU mesh.

The key distributed-correctness assertion (the reference never had one,
SURVEY.md §4): data-parallel training over 8 shards produces the SAME
parameter update as single-device training on the full batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.data.synthetic import SyntheticImageDataset
from distributeddeeplearning_tpu.models.resnet import ResNet
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.training import (
    create_train_state,
    make_eval_step,
    make_train_step,
)
from distributeddeeplearning_tpu.training.train_step import replicate_state

CFG = TrainConfig(
    model="resnet18",
    num_classes=10,
    image_size=16,
    batch_size_per_device=2,
    weight_decay=1e-4,
    compute_dtype="float32",
)


def _model():
    return ResNet(depth=18, num_classes=10, dtype=jnp.float32)


def _batch(global_batch=16, seed=0):
    rng = np.random.RandomState(seed)
    images = rng.randn(global_batch, 16, 16, 3).astype(np.float32)
    labels = rng.randint(0, 10, size=(global_batch,)).astype(np.int32)
    return images, labels


@pytest.fixture(scope="module")
def setup(mesh8):
    model = _model()
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_train_state(model, CFG, tx, input_shape=(1, 16, 16, 3))
    state = replicate_state(state, mesh8)
    step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    return model, tx, state, step


def test_train_step_runs_and_metrics(setup, mesh8):
    _, _, state, step = setup
    batch = shard_batch(_batch(), mesh8)
    new_state, metrics = step(state, batch)
    assert int(new_state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0
    assert float(metrics["grad_norm"]) > 0.0


def test_loss_decreases_on_fixed_batch(mesh8):
    # Plain SGD, no momentum/wd: with BN, conv kernels are scale-invariant
    # and momentum inflates their norm without changing CE, which would
    # make a loss that *includes* the L2 term non-monotone.
    model = _model()
    tx = optax.sgd(0.01)
    cfg = CFG.replace(weight_decay=0.0)
    state = replicate_state(
        create_train_state(model, cfg, tx, input_shape=(1, 16, 16, 3)), mesh8
    )
    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    batch = shard_batch(_batch(), mesh8)
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_dp_matches_single_device(mesh8):
    """8-way sharded update == single-device full-batch update.

    BN caveat: per-replica BN statistics (reference parity) make the
    *forward* differ between 1 and 8 shards, so for this equivalence test
    the batch is constructed so each shard has identical contents — then
    local BN stats equal global stats and updates must match exactly.
    """
    model = _model()
    tx = optax.sgd(0.1)
    state = create_train_state(model, CFG, tx, input_shape=(1, 16, 16, 3))

    shard_imgs, shard_labels = _batch(global_batch=2, seed=3)
    images = np.tile(shard_imgs, (8, 1, 1, 1))
    labels = np.tile(shard_labels, 8)

    # single-device reference update (no mesh)
    mesh1 = create_mesh(devices=jax.devices()[:1])
    step1 = make_train_step(model, tx, mesh1, CFG, donate_state=False)
    s1 = replicate_state(state, mesh1)
    s1, m1 = step1(s1, shard_batch((images, labels), mesh1))

    step8 = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    s8 = replicate_state(state, mesh8)
    s8, m8 = step8(s8, shard_batch((images, labels), mesh8))

    # Compare the parameter *updates* by relative norm: f32 reduction-order
    # noise (16-sample reduce vs 8x2-shard + pmean, BN rsqrt) stays well
    # under 5%, while the bug class this guards (sum-instead-of-mean
    # gradient reduction) produces a ratio near 7.
    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]), rtol=1e-4)
    for p0, a, b in zip(
        jax.tree.leaves(state.params),
        jax.tree.leaves(s1.params),
        jax.tree.leaves(s8.params),
    ):
        d1 = np.asarray(a) - np.asarray(p0)
        d8 = np.asarray(b) - np.asarray(p0)
        denom = np.linalg.norm(d1) + 1e-12
        assert np.linalg.norm(d8 - d1) / denom < 0.05


def test_eval_step(setup, mesh8):
    model, _, state, _ = setup
    eval_step = make_eval_step(model, mesh8)
    metrics = eval_step(state, shard_batch(_batch(), mesh8))
    for k in ("loss", "top1", "top5"):
        assert np.isfinite(float(metrics[k]))
    assert float(metrics["top5"]) >= float(metrics["top1"])
    assert float(metrics["count"]) == 16.0


def test_eval_step_masks_padded_samples(setup, mesh8):
    """Zero-weight slots must not affect metrics: same real samples with
    different garbage in the padded slots → identical metrics, count=10."""
    model, _, state, _ = setup
    eval_step = make_eval_step(model, mesh8)
    images, labels = _batch()
    weights = np.array([1.0] * 10 + [0.0] * 6, np.float32)

    def with_garbage(seed):
        rng = np.random.RandomState(seed)
        im = images.copy()
        lb = labels.copy()
        im[10:] = rng.randn(6, 16, 16, 3) * 50
        lb[10:] = rng.randint(0, 10, size=(6,))
        return im, lb, weights

    m1 = eval_step(state, shard_batch(with_garbage(1), mesh8))
    m2 = eval_step(state, shard_batch(with_garbage(2), mesh8))
    assert float(m1["count"]) == 10.0
    for k in ("loss", "top1", "top5"):
        np.testing.assert_allclose(float(m1[k]), float(m2[k]), rtol=1e-6)


def test_exact_evaluation_covers_every_sample_once(setup, mesh8):
    """Engine-level: synthetic exact val set of 100 @ global batch 16 →
    7 lockstep batches, exactly 100 weighted samples."""
    from distributeddeeplearning_tpu.training import loop

    model, _, state, _ = setup
    ds = SyntheticImageDataset(
        length=100,
        global_batch_size=16,
        image_size=16,
        num_classes=10,
        num_physical_batches=2,
        exact=True,
    )
    assert ds.steps_per_epoch == 7  # ceil(100/16)
    metrics = loop.evaluate(model, CFG, ds, state, mesh=mesh8)
    assert metrics["samples"] == 100.0
    for k in ("loss", "top1", "top5"):
        assert np.isfinite(metrics[k])


def test_synthetic_pipeline_through_train_step(setup, mesh8):
    _, _, state, step = setup
    ds = SyntheticImageDataset(
        length=64,
        global_batch_size=16,
        image_size=16,
        num_classes=10,
        num_physical_batches=2,
        seed=7,
    )
    n = 0
    for images, labels in ds.epoch(0):
        state, metrics = step(state, shard_batch((images, labels), mesh8))
        n += 1
    assert n == ds.steps_per_epoch == 4
    assert int(state.step) == 4


def test_weight_decay_changes_grads(mesh8):
    model = _model()
    tx = optax.sgd(0.1)
    cfg_nowd = CFG.replace(weight_decay=0.0)
    state = create_train_state(model, CFG, tx, input_shape=(1, 16, 16, 3))
    batch = shard_batch(_batch(), mesh8)

    s_wd = replicate_state(state, mesh8)
    s_nw = replicate_state(state, mesh8)
    _, m_wd = make_train_step(model, tx, mesh8, CFG, donate_state=False)(s_wd, batch)
    _, m_nw = make_train_step(model, tx, mesh8, cfg_nowd, donate_state=False)(
        s_nw, batch
    )
    assert float(m_wd["loss"]) > float(m_nw["loss"])  # L2 penalty added


def test_replica_axis_mesh_matches_plain_dp(mesh8):
    """Multi-slice shape: a (replica=2, data=4) mesh — replica is the
    DCN-outer axis — computes the identical update to the flat 8-way
    data mesh (the batch shards over replica×data and grads pmean over
    both axes)."""
    model = _model()
    tx = optax.sgd(0.1, momentum=0.9)
    images, labels = _batch()

    results = []
    for mesh in (
        create_mesh(axes=("replica", "data"), shape=(2, 4)),
        mesh8,
    ):
        state = replicate_state(create_train_state(model, CFG, tx), mesh)
        step = make_train_step(model, tx, mesh, CFG, donate_state=False)
        state, metrics = step(state, shard_batch((images, labels), mesh))
        results.append((float(metrics["loss"]), jax.device_get(state.params)))
    assert np.isclose(results[0][0], results[1][0], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(results[0][1]), jax.tree.leaves(results[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_sparse_ce_custom_vjp_matches_ad_reference():
    """The scatter-free CE backward (custom VJP) against plain AD of the
    take_along_axis/one-hot formulations, values and grads, with and
    without label smoothing, [B,C] and [B,T,C]."""
    from distributeddeeplearning_tpu.training.train_step import (
        cross_entropy_loss,
    )

    def ref_ce(logits, labels, ls=0.0):
        c = logits.shape[-1]
        if ls > 0.0:
            on, off = 1.0 - ls, ls / (c - 1)
            targets = jax.nn.one_hot(labels, c) * (on - off) + off
            return -jnp.mean(
                jnp.sum(targets * jax.nn.log_softmax(logits), axis=-1)
            )
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.take_along_axis(logp, labels[..., None], axis=-1)
        )

    rng = np.random.RandomState(0)
    for shape, ls in [((8, 16), 0.0), ((8, 16), 0.1),
                      ((2, 5, 16), 0.0), ((2, 5, 16), 0.1)]:
        logits = jnp.asarray(rng.randn(*shape).astype(np.float32)) * 3
        labels = jnp.asarray(rng.randint(0, 16, shape[:-1]).astype(np.int32))
        v_new, g_new = jax.value_and_grad(
            lambda l: cross_entropy_loss(l, labels, ls)
        )(logits)
        v_ref, g_ref = jax.value_and_grad(
            lambda l: ref_ce(l, labels, ls)
        )(logits)
        np.testing.assert_allclose(float(v_new), float(v_ref), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(g_new), np.asarray(g_ref), atol=1e-5
        )
