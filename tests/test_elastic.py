"""Elastic-worlds oracles (ISSUE 11): topology-independent checkpoints,
shrink-to-survivors restart, grow-back.

Tiers:

* fast — the FAULT_PLAN elasticity grammar (shrink/restore_capacity),
  the capacity-probe file protocol, divisor-compatible world selection,
  the process-count-independent "global" data topology, the checkpoint
  **portability oracle** (save on an 8-device mesh; restore + reshard
  onto 1, 4 and 8 devices — params bitwise-identical as global arrays,
  optimizer state round-trips, manifest intact), ``reshard_state``,
  the faultgen elastic-drill CLI, bench_trend's ``world_change`` skip,
  and a jax-light supervisor e2e driving the whole
  shrink→resume→grow cycle in seconds (``tests/_fault_child.py``).
* heavy (``tests/heavy_tests.txt``) — the in-process trajectory oracle:
  an lm_tiny world preempted mid-epoch resumes on HALF the devices with
  ``BATCHSIZE``/``ACCUM_STEPS`` doubled (effective batch constant, LR
  world pinned) and the post-resume trajectory matches the uninterrupted
  fixed-world run at f32-ULP; a grow-back resumes on the full mesh and
  the final params still match. The real 2-OS-process supervised drill
  lives in ``tests/test_fault_tolerance.py``.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributeddeeplearning_tpu import faults
from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.training.checkpoint import (
    CheckpointManager,
    build_manifest,
    reshard_state,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB, T = 64, 16


# ---------------------------------------------------------------------------
# Fast: elasticity grammar + capacity probe
# ---------------------------------------------------------------------------

def test_parse_elastic_plan_grammar():
    plan = faults.parse_fault_plan(
        "shrink:step=3,ranks=2;restore_capacity:secs=30"
    )
    assert plan[0] == faults.Fault(kind="shrink", step=3, ranks=2)
    assert plan[1].kind == "restore_capacity"
    assert plan[1].step == 0 and plan[1].secs == 30.0
    # step-indexed restore (the deterministic drill form)
    plan = faults.parse_fault_plan("shrink:step=2;restore_capacity:step=6")
    assert plan[0].ranks == 1
    assert plan[1].step == 6


@pytest.mark.parametrize(
    "bad",
    [
        "kill:step=1,ranks=2",      # ranks is shrink-only
        "restore_capacity:",        # needs secs= or step=
        "shrink:ranks=1",           # missing step
        "shrink:step=1,ranks=0",    # must lose >= 1 process
    ],
)
def test_parse_elastic_plan_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_fault_plan(bad)


def test_capacity_probe_protocol(tmp_path):
    cap = str(tmp_path / "capacity.json")
    # no file / unreadable file -> full capacity (never block a relaunch)
    assert faults.probe_capacity(cap, 8) == 8
    assert faults.probe_capacity(None, 8) == 8
    (tmp_path / "capacity.json").write_text("{torn")
    assert faults.probe_capacity(cap, 8) == 8
    faults.write_capacity(cap, 3)
    assert faults.probe_capacity(cap, 8) == 3
    # a recorded restore_at in the past means capacity came back
    faults.write_capacity(cap, 3, restore_at=time.time() - 1)
    assert faults.probe_capacity(cap, 8) == 8
    faults.write_capacity(cap, 3, restore_at=time.time() + 3600)
    assert faults.probe_capacity(cap, 8) == 3
    # clamped to [0, full]
    faults.write_capacity(cap, 99)
    assert faults.probe_capacity(cap, 8) == 8


def test_elastic_world_selection():
    from distributeddeeplearning_tpu.launch import _elastic_world

    # largest divisor of the full world that fits available capacity
    assert _elastic_world(8, 8, 1) == 8
    assert _elastic_world(8, 7, 1) == 4
    assert _elastic_world(8, 3, 1) == 2
    assert _elastic_world(2, 1, 1) == 1
    # the operator's floor wins over availability
    assert _elastic_world(8, 1, 2) == 2
    assert _elastic_world(2, 0, 1) == 1
    # floor above every divisor -> full world
    assert _elastic_world(4, 0, 5) == 4


def test_injector_shrink_writes_capacity_and_spares_survivors(
    tmp_path, monkeypatch
):
    """The shrink verb's split personality: every rank records the lost
    capacity, only the top ``ranks`` casualties die. Rank 0 of a
    2-process world survives a ranks=1 shrink — so we can assert the
    capacity file (a SIGKILLed process asserts nothing)."""
    cap = str(tmp_path / "capacity.json")
    plan = faults.parse_fault_plan(
        "shrink:step=2,ranks=1;restore_capacity:secs=45"
    )
    inj = faults.FaultInjector(
        plan, rank=0, world=2, capacity_file=cap
    )
    assert inj.restore_secs == 45.0
    assert inj.due_after(2)
    t0 = time.time()
    inj.fire_after(2)  # rank 0 < survivors(1): returns alive
    d = json.loads((tmp_path / "capacity.json").read_text())
    assert d["available"] == 1
    assert t0 + 40 <= d["restore_at"] <= time.time() + 50
    # one-shot: fired directives are gone
    assert not inj.due_after(2)


def test_injector_restore_capacity_step_announces_full_world(
    tmp_path,
):
    cap = str(tmp_path / "capacity.json")
    inj = faults.FaultInjector(
        faults.parse_fault_plan("restore_capacity:step=5"),
        rank=0, world=1, full_world=2, capacity_file=cap,
    )
    assert inj.due_after(5)
    inj.fire_after(5)  # announces capacity and RETURNS (run continues)
    assert faults.probe_capacity(cap, 2) == 2
    assert json.loads((tmp_path / "capacity.json").read_text())[
        "available"
    ] == 2


def test_faultgen_elastic_drill_cli():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    res = subprocess.run(
        [sys.executable, "scripts/faultgen.py", "elastic-drill",
         "--step", "3", "--ranks", "1", "--restore-step", "6"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120, env=env,
    )
    assert res.returncode == 0, res.stderr
    assert res.stdout.strip() == (
        "shrink:step=3,ranks=1;restore_capacity:step=6"
    )
    # the emitted plan validates
    val = subprocess.run(
        [sys.executable, "scripts/faultgen.py", "validate",
         res.stdout.strip()],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120, env=env,
    )
    assert val.returncode == 0, val.stderr
    assert "shrink" in val.stdout and "restore_capacity" in val.stdout
    # wall-clock form + exit-code table carries the resize code
    secs = subprocess.run(
        [sys.executable, "scripts/faultgen.py", "elastic-drill",
         "--restore-secs", "12"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120, env=env,
    )
    assert secs.stdout.strip().endswith("restore_capacity:secs=12")
    codes = subprocess.run(
        [sys.executable, "scripts/faultgen.py", "exit-codes"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120, env=env,
    )
    assert "world_resize" in codes.stdout


def test_config_elastic_env_contract():
    cfg = TrainConfig.from_env({
        "ELASTIC": "1",
        "LR_WORLD_SIZE": "8",
        "DATA_TOPOLOGY": "global",
        "COMPUTE_DTYPE": "float32",
    })
    assert cfg.elastic is True
    assert cfg.lr_world_size == 8
    assert cfg.data_topology == "global"
    assert cfg.compute_dtype == "float32"
    d = TrainConfig.from_env({})
    assert d.elastic is False and d.lr_world_size is None
    assert d.data_topology == "process"
    from distributeddeeplearning_tpu.training.loop import resolve_engine

    with pytest.raises(ValueError, match="DATA_TOPOLOGY"):
        resolve_engine(d.replace(data_topology="sideways"))
    with pytest.raises(ValueError, match="LR_WORLD_SIZE"):
        resolve_engine(d.replace(lr_world_size=0))


# ---------------------------------------------------------------------------
# Fast: process-count-independent ("global") data topology
# ---------------------------------------------------------------------------

def test_global_topology_token_stream_is_world_size_invariant():
    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )

    kw = dict(length=32, global_batch_size=8, seq_len=4, vocab_size=17,
              topology="global")
    one = SyntheticTokenDataset(**kw)
    two = [
        SyntheticTokenDataset(
            **kw, process_index=i, process_count=2
        )
        for i in range(2)
    ]
    for e in (0, 1):
        s1 = list(one.epoch(e))
        s2a, s2b = list(two[0].epoch(e)), list(two[1].epoch(e))
        for k in range(len(s1)):
            for part in (0, 1):  # inputs and targets
                np.testing.assert_array_equal(
                    s1[k][part],
                    np.concatenate([s2a[k][part], s2b[k][part]], axis=0),
                )
    # single-process global topology is BITWISE the legacy stream, so
    # turning it on does not invalidate any recorded single-host run
    legacy = SyntheticTokenDataset(
        length=32, global_batch_size=8, seq_len=4, vocab_size=17
    )
    for (a1, b1), (a2, b2) in zip(one.epoch(0), legacy.epoch(0)):
        np.testing.assert_array_equal(a1, a2)
        np.testing.assert_array_equal(b1, b2)


def test_global_topology_image_stream_is_world_size_invariant():
    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticImageDataset,
    )

    kw = dict(length=32, global_batch_size=8, image_size=4, num_classes=3,
              topology="global")
    one = SyntheticImageDataset(**kw)
    parts = [
        SyntheticImageDataset(**kw, process_index=i, process_count=4)
        for i in range(4)
    ]
    s1 = list(one.epoch(1))
    sp = [list(d.epoch(1)) for d in parts]
    for k in range(len(s1)):
        np.testing.assert_array_equal(
            s1[k][0], np.concatenate([s[k][0] for s in sp], axis=0)
        )
        np.testing.assert_array_equal(
            s1[k][1], np.concatenate([s[k][1] for s in sp], axis=0)
        )
    # exact mode: padded tail weights are against the GLOBAL length
    ex = SyntheticImageDataset(
        length=10, global_batch_size=8, image_size=4, num_classes=3,
        topology="global", exact=True,
    )
    w = np.concatenate([b[2] for b in ex.epoch(0)])
    assert w.sum() == 10
    with pytest.raises(ValueError, match="topology"):
        SyntheticImageDataset(
            length=8, global_batch_size=8, image_size=4, num_classes=3,
            topology="diagonal",
        )


# ---------------------------------------------------------------------------
# Fast: checkpoint portability oracle (save on 8, restore on 1 / 4 / 8)
# ---------------------------------------------------------------------------

def _submeshes(devices):
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh

    return {
        1: create_mesh(devices=devices[:1]),
        4: create_mesh(devices=devices[:4]),
        8: create_mesh(devices=devices),
    }


def _toy_state(mesh, fill=None):
    """A TrainState with real optax momentum state, data-sharded and
    replicated leaves — the sharding shapes a real run produces."""
    import optax

    from distributeddeeplearning_tpu.training.state import TrainState

    params = {
        "kernel": jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
        "bias": jnp.arange(4, dtype=jnp.float32),
    }
    if fill is not None:
        params = jax.tree.map(lambda x: x * 0 + fill, params)
    tx = optax.sgd(1e-2, momentum=0.9)
    state = TrainState.create(
        params=params, batch_stats={}, tx=tx
    )
    return jax.device_put(state, NamedSharding(mesh, P())), tx


def test_checkpoint_portability_across_device_counts(tmp_path, devices):
    """The portability oracle: save a real TrainState (params + sgd
    momentum + step) from the 8-device mesh; restore onto 1-, 4- and
    8-device meshes — every leaf bitwise-identical as a global array,
    the optimizer state round-tripping, the manifest decoding the same
    data cursor everywhere."""
    meshes = _submeshes(devices)
    state8, _ = _toy_state(meshes[8])
    # make momentum non-trivial so opt_state round-trip means something
    import optax

    grads = jax.tree.map(jnp.ones_like, state8.params)
    tx = optax.sgd(1e-2, momentum=0.9)
    updates, new_opt = tx.update(grads, state8.opt_state, state8.params)
    state8 = state8.replace(
        params=optax.apply_updates(state8.params, updates),
        opt_state=new_opt,
        step=state8.step + 1,
    )

    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d, save_every_steps=1, async_save=False)
    assert mgr.save_step(
        6, state8,
        manifest=build_manifest(
            global_step=6, steps_per_epoch=4, effective_batch=16,
            accum_steps=1,
        ),
    )
    mgr.close()

    want = jax.device_get(state8)
    for n, mesh in meshes.items():
        template, _ = _toy_state(mesh, fill=0.0)
        mgr2 = CheckpointManager(d, save_every_steps=1, async_save=False)
        got, epoch, skip = mgr2.maybe_restore_at(
            template, steps_per_epoch=4
        )
        # manifest decodes the cursor identically on every topology
        assert (epoch, skip) == (1, 2)
        assert mgr2.last_manifest["effective_batch"] == 16
        assert mgr2.last_manifest["world_size"] == 8
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(want),
            jax.tree_util.tree_leaves_with_path(jax.device_get(got)),
        ):
            assert str(pa) == str(pb)
            np.testing.assert_array_equal(a, b, err_msg=f"{n}-dev {pa}")
        # the restored arrays actually live on the target mesh
        leaf = jax.tree.leaves(got)[0]
        assert set(leaf.sharding.device_set) <= set(mesh.devices.flat)
        mgr2.close()


def test_reshard_state_roundtrip_bitwise(devices):
    meshes = _submeshes(devices)
    x8 = jax.device_put(
        jnp.arange(16, dtype=jnp.float32),
        NamedSharding(meshes[8], P("data")),
    )
    r8 = jax.device_put(
        jnp.arange(4, dtype=jnp.float32) * 3, NamedSharding(meshes[8], P())
    )
    state = {"w": x8, "b": r8}
    tmpl4 = {
        "w": jax.ShapeDtypeStruct(
            (16,), jnp.float32,
            sharding=NamedSharding(meshes[4], P("data")),
        ),
        "b": jax.ShapeDtypeStruct(
            (4,), jnp.float32, sharding=NamedSharding(meshes[4], P())
        ),
    }
    down = reshard_state(state, tmpl4)
    assert set(down["w"].sharding.device_set) == set(
        meshes[4].devices.flat
    )
    tmpl8 = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=x.sharding
        ),
        state,
    )
    back = reshard_state(down, tmpl8)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(x8))
    np.testing.assert_array_equal(np.asarray(back["b"]), np.asarray(r8))
    # global shapes are the contract: a mismatch is refused loudly
    with pytest.raises(ValueError, match="shape"):
        reshard_state(
            {"w": jnp.arange(8, dtype=jnp.float32)},
            {"w": tmpl4["w"]},
        )


# ---------------------------------------------------------------------------
# Fast: bench_trend world_change skip
# ---------------------------------------------------------------------------

def test_bench_trend_world_change_is_skip_not_regression(tmp_path):
    from scripts.bench_trend import analyze

    def rec(n, value, world=None):
        detail = {"platform": "cpu"}
        if world is not None:
            detail["world_size"] = world
        path = tmp_path / f"BENCH_r{n:02d}.json"
        path.write_text(json.dumps({
            "n": n, "rc": 0,
            "parsed": {"metric": "resnet50_imgs_per_sec", "value": value,
                       "unit": "img/s", "detail": detail},
        }))
        return str(path)

    paths = [
        rec(1, 1000.0, world=8),
        rec(2, 400.0, world=4),   # elastic resize: new baseline, NOT a drop
        rec(3, 395.0, world=4),   # like-for-like: fine (-1.2%)
        rec(4, 100.0, world=4),   # like-for-like: REAL regression
    ]
    out = analyze(paths, threshold=0.10)
    rows = {r["round"]: r for r in out["rows"]}
    assert rows[2]["skip"] == "world_change:8->4"
    assert rows[3]["skip"] is None and rows[3]["delta_pct"] is not None
    assert len(out["regressions"]) == 1
    assert out["regressions"][0]["to_round"] == 4
    # legacy records (no world field) normalize together and stay comparable
    legacy = [rec(5, 500.0), rec(6, 490.0)]
    out2 = analyze(legacy, threshold=0.10)
    assert out2["ok"]
    assert all(r["skip"] in (None, "world_change:4->unspecified")
               for r in out2["rows"])


# ---------------------------------------------------------------------------
# Fast: jax-light supervisor e2e — the whole shrink→resume→grow cycle
# ---------------------------------------------------------------------------

def _run_launcher(args, timeout=600):
    return subprocess.run(
        [sys.executable, "launch.py", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=timeout,
    )


def test_supervisor_elastic_shrink_and_grow_jaxlight(tmp_path):
    """launch.py --elastic over the jax-light child: a shrink preemption
    kills the top rank of a 2-process world and records lost capacity;
    the supervisor relaunches at world 1 with BATCHSIZE/ACCUM_STEPS
    doubled and LR_WORLD_SIZE pinned; the shrunken world announces
    restored capacity at a later step; the grow poller stops it with the
    resize code (no restart budget burned) and relaunches at full size,
    which resumes and completes."""
    obs_dir = tmp_path / "run"
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--max-restarts", "1",
            "--restart-backoff", "0.1",
            "--elastic",
            "--min-world-size", "1",
            "--grow-check-every-s", "0.2",
            "--timeout", "120",
            "--obs-dir", str(obs_dir),
            "--env", "JAX_PLATFORMS=cpu",
            "--env", "FAKE_STEPS=40",
            "--env", "BATCHSIZE=2",
            "--env", "ACCUM_STEPS=1",
            # rank=1 pins the directive to the casualty process, so the
            # world-1 relaunch (rank 0) can never re-fire it whatever
            # step its state file persisted before the teardown
            "--env",
            "FAULT_PLAN=shrink:step=3,rank=1,ranks=1;"
            "restore_capacity:step=6",
            "--env", f"STATE_FILE={tmp_path}/state",
            "tests/_fault_child.py",
        ],
        timeout=300,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    # attempt 0: full world, base geometry
    assert "FAULT_CHILD_WORLD rank=0 world=2 batch=2 accum=1 lr_world=2" in out
    # shrink classified as a retryable signal death; relaunch at world 1
    # with the integer rescale (effective batch held constant)
    assert "rc=-9, signal_SIGKILL" in out
    assert (
        "supervisor: elastic world 1/2 processes — BATCHSIZE 2->4, "
        "ACCUM_STEPS 1->2" in out
    ), out[-4000:]
    assert "FAULT_CHILD_WORLD rank=0 world=1 batch=4 accum=2 lr_world=2" in out
    # the shrunken world resumed from persisted progress, not step 0
    # (rank 0 survived to at least the shrink step before teardown)
    # grow-back: resize stop (rc 95) burns no budget, full world resumes
    assert "launch: world resize requested (capacity restored" in out
    assert "supervisor: world resize 1 -> 2" in out
    assert "no restart budget consumed" in out
    assert "FAULT_CHILD_WORLD rank=1 world=2 batch=2 accum=1 lr_world=2" in out
    assert "FAULT_CHILD_DONE 0" in out and "FAULT_CHILD_DONE 1" in out
    # capacity file went through the full protocol
    cap = json.loads((obs_dir / "capacity.json").read_text())
    assert cap["available"] == 2  # restore_capacity announced full world
    # supervisor record: per-attempt world sizes + the resize event
    recs = [
        json.loads(ln) for ln in open(obs_dir / "events-supervisor.jsonl")
    ]
    starts = [
        r["labels"]["world_size"] for r in recs
        if r.get("name") == "attempt_start"
    ]
    assert starts == [2, 1, 2], starts
    resized = [r for r in recs if r.get("name") == "elastic.world_resized"]
    assert any(
        r["labels"]["phase"] == "grow"
        and r["labels"]["from_world"] == 1
        and r["labels"]["to_world"] == 2
        for r in resized
    ), resized
    # shrink flight dump: the casualty left its black box
    dumps = list(obs_dir.glob("flight-p1*.jsonl"))
    assert dumps, sorted(os.listdir(obs_dir))
    head = json.loads(open(dumps[0]).readline())
    assert head["reason"] == "fault_shrink"


def test_supervisor_elastic_respects_min_world_size(tmp_path):
    """MIN_WORLD_SIZE=2 on a 2-process world: the shrink's capacity loss
    cannot go below the floor, so the supervisor relaunches at FULL size
    (the only divisor >= the floor) — and the run, resumed past the
    one-shot shrink step, completes."""
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--max-restarts", "2",
            "--restart-backoff", "0.1",
            "--elastic",
            "--min-world-size", "2",
            "--timeout", "120",
            "--env", "JAX_PLATFORMS=cpu",
            "--env", "FAKE_STEPS=6",
            "--env", "FAULT_PLAN=shrink:step=3,ranks=1",
            "--env", f"STATE_FILE={tmp_path}/state",
            "tests/_fault_child.py",
        ],
        timeout=300,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "FAULT_CHILD_WORLD rank=1 world=2" in out
    # no rescale announcement: the floor kept the world at full size
    assert "supervisor: elastic world" not in out
    assert "world=1" not in out
    assert "FAULT_CHILD_DONE 1 start=3" in out, out[-4000:]


# ---------------------------------------------------------------------------
# Heavy: in-process elastic trajectory oracle (registered in
# tests/heavy_tests.txt)
# ---------------------------------------------------------------------------

def _lm_cfg(**kw):
    base = dict(
        model="lm_tiny",
        num_classes=VOCAB,
        batch_size_per_device=2,
        fake_data_length=64,
        epochs=3,
        compute_dtype="float32",
        weight_decay=0.0,
        log_every_steps=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _lm_fit(cfg, mesh):
    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    data = SyntheticTokenDataset(
        length=cfg.fake_data_length,
        global_batch_size=16,  # constant at every world size
        seq_len=T,
        vocab_size=VOCAB,
    )
    model = get_model(
        "lm_tiny", num_classes=VOCAB, dtype="float32", max_seq_len=T
    )
    return loop.fit(model, cfg, data, mesh=mesh, add_default_logger=False)


def _ulp_equal(tree_a, tree_b):
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(jax.device_get(tree_a)),
        jax.tree_util.tree_leaves_with_path(jax.device_get(tree_b)),
    ):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-7,
                                   err_msg=str(pa))


def test_inprocess_elastic_shrink_grow_is_ulp_equivalent(
    tmp_path, devices, monkeypatch
):
    """The elastic math contract, in one process: preempt a mesh8 run
    mid-epoch; resume on mesh4 with BATCHSIZE x2 + ACCUM_STEPS x2 and
    the LR world pinned (effective batch 16 everywhere); the resumed
    trajectory matches the uninterrupted mesh8 run at f32-ULP; grow
    back onto mesh8 for the final epoch and the final params still
    match. Also asserts the elastic telemetry (world_resized /
    reshard_ms / data.resume_skip) and the steady-state sync invariant.
    """
    import shutil

    from distributeddeeplearning_tpu import obs
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.utils import hostsync

    mesh8 = create_mesh(devices=devices)
    mesh4 = create_mesh(devices=devices[:4])

    # References: uninterrupted fixed world at 3 epochs (the final
    # comparison) and at 2 (the shrunken leg's endpoint) — the first
    # under the sync accountant, proving elasticity added ZERO host
    # syncs to the steady-state loop (no step checkpoints here; the one
    # sync per epoch stands).
    hostsync.accountant().reset()
    with hostsync.track():
        ref = _lm_fit(_lm_cfg(elastic=True, lr_world_size=8), mesh8)
    assert hostsync.accountant().count == 3, hostsync.accountant().by_label
    ref2 = _lm_fit(_lm_cfg(epochs=2), mesh8)

    ckpt_dir = str(tmp_path / "ckpt")
    cfg8 = _lm_cfg(
        model_dir=ckpt_dir, checkpoint_every_steps=1, checkpoint_async=False,
        lr_world_size=8, checkpoint_keep=20,
    )
    full = _lm_fit(cfg8, mesh8)
    _ulp_equal(ref.state.params, full.state.params)  # ckpt is neutral

    # Preempt at step 6 (4 steps/epoch -> mid-epoch-1, 2 batches done).
    for s in faults.checkpoint_steps(ckpt_dir):
        if s > 6:
            shutil.rmtree(os.path.join(ckpt_dir, str(s)))
    assert faults.checkpoint_steps(ckpt_dir)[-1] == 6

    obs_dir = tmp_path / "obs"
    monkeypatch.setenv("OBS_DIR", str(obs_dir))
    shrunk = _lm_fit(
        _lm_cfg(
            model_dir=ckpt_dir, checkpoint_every_steps=1,
            checkpoint_async=False, batch_size_per_device=4, accum_steps=2,
            lr_world_size=8, elastic=True, epochs=2, checkpoint_keep=20,
        ),
        mesh4,
    )
    monkeypatch.delenv("OBS_DIR")
    obs.reset()
    # The resume REALLY re-entered mid-epoch: only the 2 remaining
    # batches of epoch 1 ran (2 x global batch 16 = 32 images), and the
    # post-resume params land ULP-equal to the fixed-world 2-epoch run.
    assert len(shrunk.history) == 1
    assert shrunk.history[-1]["global_step"] == 8
    assert shrunk.history[-1]["epoch_images"] == 32
    _ulp_equal(ref2.state.params, shrunk.state.params)
    # elastic telemetry: cross-topology restore reported the reshard +
    # the O(step) resume replay reported its cost
    events = []
    for p in sorted(obs_dir.glob("events-*.jsonl")):
        events += [json.loads(ln) for ln in open(p)]
    names = [e.get("name") for e in events]
    assert "elastic.world_resized" in names
    resized = next(
        e for e in events if e.get("name") == "elastic.world_resized"
    )
    assert resized["labels"]["from_world"] == 8
    assert resized["labels"]["to_world"] == 4
    assert "elastic.reshard_ms" in names
    skip_ev = next(e for e in events if e.get("name") == "data.resume_skip")
    assert skip_ev["labels"]["skipped"] == 2
    assert "data.resume_skip_ms" in names

    # Grow back: full mesh for the last epoch, resuming the mesh4 world's
    # checkpoint — the post-resume loss trajectory and the final params
    # (and optimizer state) match the uninterrupted run at f32-ULP.
    grown = _lm_fit(
        _lm_cfg(
            model_dir=ckpt_dir, checkpoint_every_steps=1,
            checkpoint_async=False, lr_world_size=8, elastic=True,
            checkpoint_keep=20,
        ),
        mesh8,
    )
    assert grown.history[-1]["global_step"] == 12
    np.testing.assert_allclose(
        grown.history[-1]["loss"], ref.history[-1]["loss"],
        rtol=1e-4, atol=1e-6,
    )
    _ulp_equal(ref.state.params, grown.state.params)
    _ulp_equal(ref.state.opt_state, grown.state.opt_state)


def test_elastic_resume_refuses_wrong_effective_batch(
    tmp_path, devices
):
    """The accum-rescale validation: resuming an elastic world at a
    DIFFERENT effective batch (shrunken devices without the BATCHSIZE
    rescale) is refused with the contract named; with ELASTIC off the
    same mismatch only warns."""
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh

    mesh8 = create_mesh(devices=devices)
    mesh4 = create_mesh(devices=devices[:4])
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = _lm_cfg(
        model_dir=ckpt_dir, checkpoint_every_steps=1,
        checkpoint_async=False, epochs=1,
    )
    _lm_fit(cfg, mesh8)

    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    bad = _lm_cfg(
        model_dir=ckpt_dir, checkpoint_every_steps=1,
        checkpoint_async=False, elastic=True, epochs=2,
    )  # still 2/device, but only 4 shards -> effective 8 != 16
    data = SyntheticTokenDataset(
        length=bad.fake_data_length, global_batch_size=8, seq_len=T,
        vocab_size=VOCAB,
    )
    model = get_model(
        "lm_tiny", num_classes=VOCAB, dtype="float32", max_seq_len=T
    )
    with pytest.raises(ValueError, match="effective batch"):
        loop.fit(model, bad, data, mesh=mesh4, add_default_logger=False)
