"""Child process for the true multi-process tests (run via launch.py).

This is the code every rank of a 2-process world executes — the analogue
of the script the reference runs under ``mpirun -np 2 -H localhost:2``
(``Horovod*/00_CreateImageAndTest.ipynb`` cells 6-7). It exercises every
multi-host branch the single-process suite cannot reach:

* ``maybe_initialize`` explicit rendezvous (DDL_* contract),
* ``broadcast_from_master`` / ``allreduce_host_scalar``,
* ``shard_batch``'s ``make_array_from_process_local_data`` branch,
* a real data-parallel train step over a cross-process mesh,
* per-process TFRecord file sharding (disjoint + complete coverage).

Prints ``MP_CHILD_OK <rank>`` on success; any assertion kills the world
via the launcher's all-or-nothing exit semantics.
"""

import sys

import numpy as np

from distributeddeeplearning_tpu.parallel import collectives, distributed


def main() -> None:
    tfrecord_pattern = sys.argv[1] if len(sys.argv) > 1 else None

    assert distributed.maybe_initialize(), "DDL_* env contract not picked up"

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()
    rank = jax.process_index()

    # --- host-level collectives (reference broadcast/allreduce uses) ------
    tree = {"w": np.full((3,), float(rank), np.float32), "epoch": np.int32(rank + 5)}
    got = collectives.broadcast_from_master(tree)
    assert float(np.asarray(got["w"])[0]) == 0.0, got
    assert int(got["epoch"]) == 5, got

    avg = collectives.allreduce_host_scalar(float(rank + 1))  # (1+2)/2
    assert abs(avg - 1.5) < 1e-6, avg
    tot = collectives.allreduce_host_scalar(float(rank + 1), average=False)
    assert abs(tot - 3.0) < 1e-6, tot

    # --- global batch assembly + DP train step over both processes -------
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    cfg = TrainConfig(
        batch_size_per_device=2, image_size=32, num_classes=8, fake_data_length=64
    )
    mesh = data_parallel_mesh()
    model = ResNet(depth=18, num_classes=8, dtype=jnp.bfloat16)
    tx, _ = create_optimizer(cfg, steps_per_epoch=4)
    state = replicate_state(create_train_state(model, cfg, tx), mesh)
    step = make_train_step(model, tx, mesh, cfg)

    rng = np.random.RandomState(7 + rank)  # distinct local shards
    local = (
        rng.uniform(-1, 1, size=(8, 32, 32, 3)).astype(np.float32),
        rng.randint(0, 8, size=(8,)).astype(np.int32),
    )
    batch = shard_batch(local, mesh)
    assert batch[0].shape[0] == 16, batch[0].shape  # global, not local
    assert not batch[0].is_fully_addressable  # true cross-process array

    for _ in range(2):
        state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    assert np.isfinite(loss), loss

    # --- per-process TFRecord sharding (disjoint + complete) -------------
    if tfrecord_pattern:
        from jax.experimental import multihost_utils

        from distributeddeeplearning_tpu.data.imagenet import TFRecordImageNetDataset

        ds = TFRecordImageNetDataset(
            tfrecord_pattern,
            global_batch_size=8,
            image_size=8,
            train=False,
            process_index=rank,
            process_count=2,
            length=32,
        )
        labels = []
        for _, y, w in ds.epoch(0):  # eval path yields (img, label, weight)
            labels.extend(int(v) for v in np.asarray(y)[np.asarray(w) > 0])
        assert len(labels) == 16, len(labels)
        mine = np.sort(np.asarray(labels, np.int32))
        both = multihost_utils.process_allgather(mine)
        union = np.sort(both.reshape(-1))
        assert union.tolist() == list(range(32)), union  # disjoint + complete

    # --- multi-host GSPMD: TP with params sharded ACROSS hosts -----------
    # Axis order ("model", "data") is deliberately inverted from the
    # production convention: row-major device order would otherwise put
    # each model group entirely inside one process (devices 0-3 = host
    # 0). With model outermost, every model-parallel group takes one
    # device per row — {0,2,4,6} and {1,3,5,7} — spanning BOTH
    # processes, so the Megatron column/row-parallel collectives really
    # cross the host boundary (the branch no single-process test and no
    # data-axis-only test can reach).
    from distributeddeeplearning_tpu.models.vit import LOGICAL_RULES, ViT
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.pjit_step import (
        create_sharded_train_state,
        make_pjit_train_step,
    )

    tp_mesh = create_mesh(axes=("model", "data"), shape=(4, 2))
    # every model group must contain devices from both processes
    col0 = [tp_mesh.devices[m][0] for m in range(4)]
    assert {d.process_index for d in col0} == {0, 1}, col0
    vit = ViT(variant="ti", patch_size=16, num_classes=8, dtype=jnp.bfloat16)
    tp_cfg = cfg.replace(num_classes=8, image_size=16)
    tp_state = create_sharded_train_state(
        vit, tp_cfg, tx, tp_mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    qkv = tp_state.params["block0"]["attn"]["qkv"]["kernel"]
    assert "model" in tuple(qkv.sharding.spec), qkv.sharding
    # each model shard now lives on a cross-host group: the param is not
    # fully addressable from either process on the model axis itself
    assert not qkv.is_fully_addressable
    tp_step = make_pjit_train_step(vit, tx, tp_mesh, tp_cfg, donate_state=False)
    # The data columns of this mesh also span hosts, so a process-local
    # batch can't be assembled by concatenation; feed the SAME global
    # batch from every process as a replicated array and let the step's
    # sharding constraint reshard it onto the data axis inside jit.
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng2 = np.random.RandomState(13)  # identical on both ranks
    rep = NamedSharding(tp_mesh, P())
    with tp_mesh:
        tp_batch = (
            jax.device_put(
                rng2.uniform(-1, 1, size=(4, 16, 16, 3)).astype(np.float32), rep
            ),
            jax.device_put(rng2.randint(0, 8, size=(4,)).astype(np.int32), rep),
        )
        tp_state, tp_metrics = tp_step(tp_state, tp_batch)
    tp_loss = float(tp_metrics["loss"])
    assert np.isfinite(tp_loss), tp_loss

    print(f"MP_CHILD_OK {rank} loss={loss:.4f} tp_loss={tp_loss:.4f}")


if __name__ == "__main__":
    main()
