"""Child process for the true multi-process tests (run via launch.py).

This is the code every rank of a 2-process world executes — the analogue
of the script the reference runs under ``mpirun -np 2 -H localhost:2``
(``Horovod*/00_CreateImageAndTest.ipynb`` cells 6-7). It exercises every
multi-host branch the single-process suite cannot reach:

* ``maybe_initialize`` explicit rendezvous (DDL_* contract),
* ``broadcast_from_master`` / ``allreduce_host_scalar``,
* ``shard_batch``'s ``make_array_from_process_local_data`` branch,
* a real data-parallel train step over a cross-process mesh,
* per-process TFRecord file sharding (disjoint + complete coverage).

Prints ``MP_CHILD_OK <rank>`` on success; any assertion kills the world
via the launcher's all-or-nothing exit semantics.
"""

import sys

import numpy as np

from distributeddeeplearning_tpu.parallel import collectives, distributed


def main() -> None:
    tfrecord_pattern = sys.argv[1] if len(sys.argv) > 1 else None

    assert distributed.maybe_initialize(), "DDL_* env contract not picked up"

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()
    rank = jax.process_index()

    # --- host-level collectives (reference broadcast/allreduce uses) ------
    tree = {"w": np.full((3,), float(rank), np.float32), "epoch": np.int32(rank + 5)}
    got = collectives.broadcast_from_master(tree)
    assert float(np.asarray(got["w"])[0]) == 0.0, got
    assert int(got["epoch"]) == 5, got

    avg = collectives.allreduce_host_scalar(float(rank + 1))  # (1+2)/2
    assert abs(avg - 1.5) < 1e-6, avg
    tot = collectives.allreduce_host_scalar(float(rank + 1), average=False)
    assert abs(tot - 3.0) < 1e-6, tot

    # --- global batch assembly + DP train step over both processes -------
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_optimizer,
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    cfg = TrainConfig(
        batch_size_per_device=2, image_size=32, num_classes=8, fake_data_length=64
    )
    mesh = data_parallel_mesh()
    model = ResNet(depth=18, num_classes=8, dtype=jnp.bfloat16)
    tx, _ = create_optimizer(cfg, steps_per_epoch=4)
    state = replicate_state(create_train_state(model, cfg, tx), mesh)
    step = make_train_step(model, tx, mesh, cfg)

    rng = np.random.RandomState(7 + rank)  # distinct local shards
    local = (
        rng.uniform(-1, 1, size=(8, 32, 32, 3)).astype(np.float32),
        rng.randint(0, 8, size=(8,)).astype(np.int32),
    )
    batch = shard_batch(local, mesh)
    assert batch[0].shape[0] == 16, batch[0].shape  # global, not local
    assert not batch[0].is_fully_addressable  # true cross-process array

    for _ in range(2):
        state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    assert np.isfinite(loss), loss

    # --- per-process TFRecord sharding (disjoint + complete) -------------
    if tfrecord_pattern:
        from jax.experimental import multihost_utils

        from distributeddeeplearning_tpu.data.imagenet import TFRecordImageNetDataset

        ds = TFRecordImageNetDataset(
            tfrecord_pattern,
            global_batch_size=8,
            image_size=8,
            train=False,
            process_index=rank,
            process_count=2,
            length=32,
        )
        labels = []
        for _, y, w in ds.epoch(0):  # eval path yields (img, label, weight)
            labels.extend(int(v) for v in np.asarray(y)[np.asarray(w) > 0])
        assert len(labels) == 16, len(labels)
        mine = np.sort(np.asarray(labels, np.int32))
        both = multihost_utils.process_allgather(mine)
        union = np.sort(both.reshape(-1))
        assert union.tolist() == list(range(32)), union  # disjoint + complete

    print(f"MP_CHILD_OK {rank} loss={loss:.4f}")


if __name__ == "__main__":
    main()
