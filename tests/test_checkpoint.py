import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models.resnet import ResNet
from distributeddeeplearning_tpu.training import create_train_state, make_train_step
from distributeddeeplearning_tpu.training.checkpoint import CheckpointManager
from distributeddeeplearning_tpu.training.train_step import replicate_state

CFG = TrainConfig(num_classes=10, image_size=16, compute_dtype="float32")


def _state():
    model = ResNet(depth=18, num_classes=10, dtype=jnp.float32)
    tx = optax.sgd(0.01)
    return model, tx, create_train_state(model, CFG, tx, input_shape=(1, 16, 16, 3))


def test_save_restore_roundtrip(tmp_path, mesh8):
    model, tx, state = _state()
    state = replicate_state(state, mesh8)
    step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    rng = np.random.RandomState(0)
    batch = shard_batch(
        (rng.randn(16, 16, 16, 3).astype(np.float32),
         rng.randint(0, 10, 16).astype(np.int32)),
        mesh8,
    )
    state, _ = step(state, batch)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), save_every_epochs=1)
    assert mgr.save(0, state)
    mgr.wait()
    assert mgr.latest_epoch() == 0

    _, _, fresh = _state()
    fresh = replicate_state(fresh, mesh8)
    restored, start_epoch = mgr.maybe_restore(fresh)
    assert start_epoch == 1
    assert int(restored.step) == int(state.step) == 1
    import jax

    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored state must be usable by the compiled step directly
    restored, metrics = step(restored, batch)
    assert np.isfinite(float(metrics["loss"]))
    mgr.close()


def test_save_every_n_epochs(tmp_path, mesh8):
    _, _, state = _state()
    state = replicate_state(state, mesh8)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), save_every_epochs=2)
    assert not mgr.save(0, state)  # epoch 0 not due
    assert mgr.save(1, state)  # epoch 1 due (every 2)
    assert mgr.save(2, state, force=True)
    mgr.close()


def test_disabled_manager():
    mgr = CheckpointManager(None)
    assert not mgr.enabled
    assert not mgr.save(0, {"a": np.zeros(2)})
    assert mgr.latest_epoch() is None
    state, start = mgr.maybe_restore({"a": np.zeros(2)})
    assert start == 0
    with pytest.raises(RuntimeError):
        mgr.restore({"a": np.zeros(2)})


def test_max_to_keep(tmp_path, mesh8):
    _, _, state = _state()
    state = replicate_state(state, mesh8)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for e in range(4):
        mgr.save(e, state)
    mgr.wait()
    assert mgr.latest_epoch() == 3
    _, _, fresh = _state()
    fresh = replicate_state(fresh, mesh8)
    with pytest.raises(Exception):
        mgr.restore(fresh, epoch=0)  # garbage-collected
    mgr.close()


def test_tp_sharded_state_roundtrip(tmp_path):
    """Checkpoint/resume under tensor parallelism: a TP-sharded state
    saves and restores onto the mesh with its shardings intact."""
    import jax
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.models.vit import LOGICAL_RULES, ViT
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.pjit_step import (
        create_sharded_train_state,
    )

    mesh = create_mesh(axes=("data", "model"), shape=(2, 4))
    cfg = TrainConfig(num_classes=10, image_size=16, compute_dtype="float32")
    model = ViT(variant="ti", patch_size=16, num_classes=10, dtype=jnp.float32)
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_sharded_train_state(
        model, cfg, tx, mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    qkv_before = state.params["block0"]["attn"]["qkv"]["kernel"]
    assert "model" in tuple(qkv_before.sharding.spec)

    mgr = CheckpointManager(str(tmp_path / "tp_ckpt"))
    mgr.save(0, state, force=True)
    mgr.wait()
    mgr.close()

    # restore into a freshly-initialized (different-rng) sharded state
    mgr2 = CheckpointManager(str(tmp_path / "tp_ckpt"))
    other = create_sharded_train_state(
        model, cfg, tx, mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3),
        rng=jax.random.PRNGKey(123),
    )
    restored, epoch = mgr2.maybe_restore(other)
    mgr2.close()
    assert epoch == 1  # resume epoch = saved epoch + 1
    qkv_after = restored.params["block0"]["attn"]["qkv"]["kernel"]
    assert tuple(qkv_after.sharding.spec) == tuple(qkv_before.sharding.spec)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(qkv_after)),
        np.asarray(jax.device_get(qkv_before)),
    )


def test_ep_sharded_state_roundtrip(tmp_path):
    """Checkpoint/resume under expert parallelism: MoE expert weights
    sharded over the 'expert' axis save and restore with shardings and
    values intact."""
    import jax
    import optax

    from distributeddeeplearning_tpu.models.sharding import LOGICAL_RULES
    from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.pjit_step import (
        create_sharded_train_state,
    )

    mesh = create_mesh(axes=("data", "expert"), shape=(2, 4))
    cfg = TrainConfig(num_classes=32, compute_dtype="float32")
    model = TransformerLM(
        variant="tiny", vocab_size=32, max_seq_len=8,
        dtype=jnp.float32, moe_experts=4,
    )
    tx = optax.sgd(0.1)
    state = create_sharded_train_state(
        model, cfg, tx, mesh, LOGICAL_RULES,
        input_shape=(1, 8), input_dtype=jnp.int32,
    )
    w1_before = state.params["block1"]["moe"]["w1"]
    assert tuple(w1_before.sharding.spec)[:1] == ("expert",)

    mgr = CheckpointManager(str(tmp_path / "ep_ckpt"))
    mgr.save(0, state, force=True)
    mgr.wait()
    mgr.close()

    mgr2 = CheckpointManager(str(tmp_path / "ep_ckpt"))
    other = create_sharded_train_state(
        model, cfg, tx, mesh, LOGICAL_RULES,
        input_shape=(1, 8), input_dtype=jnp.int32,
        rng=jax.random.PRNGKey(321),
    )
    restored, epoch = mgr2.maybe_restore(other)
    mgr2.close()
    assert epoch == 1
    w1_after = restored.params["block1"]["moe"]["w1"]
    assert tuple(w1_after.sharding.spec) == tuple(w1_before.sharding.spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(w1_after)),
        np.asarray(jax.device_get(w1_before)),
    )


def test_accum_steps_checkpoint_compat(tmp_path, mesh8):
    """A checkpoint written with accum_steps=1 restores into an
    accum_steps=k engine (and vice versa): the gradient accumulator is
    scan-local — it never enters TrainState, so the state pytree is
    identical either way and drives the microbatched step directly."""
    import jax

    model, tx, state = _state()
    state = replicate_state(state, mesh8)
    plain_step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    rng = np.random.RandomState(0)
    batch = shard_batch(
        (rng.randn(16, 16, 16, 3).astype(np.float32),
         rng.randint(0, 10, 16).astype(np.int32)),
        mesh8,
    )
    state, _ = plain_step(state, batch)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), save_every_epochs=1)
    assert mgr.save(0, state)
    mgr.wait()
    mgr.close()

    # restore into a fresh state and run it through the ACCUM_STEPS=2
    # compiled step — same pytree structure, no adaptation layer
    accum_step = make_train_step(
        model, tx, mesh8, CFG.replace(accum_steps=2), donate_state=False
    )
    _, _, fresh = _state()
    fresh = replicate_state(fresh, mesh8)
    mgr2 = CheckpointManager(str(tmp_path / "ckpt"))
    restored, start_epoch = mgr2.maybe_restore(fresh)
    mgr2.close()
    assert start_epoch == 1
    assert jax.tree_util.tree_structure(restored) == (
        jax.tree_util.tree_structure(state)
    )
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    restored, metrics = accum_step(restored, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(restored.step) == 2  # one plain + one accumulated step


def test_pp_sharded_state_roundtrip(tmp_path):
    """Checkpoint/resume under pipeline parallelism: per-stage stacked
    weights (sharded over 'pipe') round-trip, and the restored state
    drives the compiled PP step."""
    import jax
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributeddeeplearning_tpu.models.pipeline_lm import PipelineLM
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.pp_step import (
        create_pp_state,
        make_pp_train_step,
    )

    mesh = create_mesh(axes=("data", "pipe"), shape=(2, 4))
    cfg = TrainConfig(num_classes=32, compute_dtype="float32",
                      weight_decay=0.0)
    pl = PipelineLM(variant="tiny", vocab_size=32, max_seq_len=8,
                    num_stages=4, n_layers=4, dtype=jnp.float32)
    tx = optax.sgd(0.1)
    state = create_pp_state(pl, cfg, tx, mesh, 8)
    step = make_pp_train_step(pl, tx, mesh, cfg, num_microbatches=2,
                              donate_state=False)
    rng = np.random.RandomState(0)
    rows = rng.randint(0, 32, size=(8, 9)).astype(np.int32)
    spec = NamedSharding(mesh, P("data"))
    batch = (jax.device_put(rows[:, :-1], spec),
             jax.device_put(rows[:, 1:], spec))
    state, _ = step(state, batch)

    mgr = CheckpointManager(str(tmp_path / "pp_ckpt"))
    mgr.save(0, state, force=True)
    mgr.wait()
    mgr.close()

    mgr2 = CheckpointManager(str(tmp_path / "pp_ckpt"))
    fresh = create_pp_state(pl, cfg, tx, mesh, 8,
                            rng=jax.random.PRNGKey(7))
    restored, epoch = mgr2.maybe_restore(fresh)
    mgr2.close()
    assert epoch == 1
    assert int(jax.device_get(restored.step)) == 1
    leaf_b = jax.tree.leaves(state.params["stages"])[0]
    leaf_a = jax.tree.leaves(restored.params["stages"])[0]
    assert tuple(leaf_a.sharding.spec)[:1] == ("pipe",)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(leaf_a)),
        np.asarray(jax.device_get(leaf_b)),
    )
    # restored state drives the compiled step directly
    restored, metrics = step(restored, batch)
    assert np.isfinite(float(metrics["loss"]))
