"""GSPMD engine tests: tensor-parallel ViT equals single-device training.

The round-1 VERDICT called TP "decorative" — LOGICAL_RULES fed a
nonexistent engine. These tests make it real: a data×model mesh shards
QKV/MLP weights Megatron-style, trains a step, and must match the
single-device update exactly (ViT has no BN, so there is no per-replica
statistics caveat).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models.resnet import ResNet
from distributeddeeplearning_tpu.models.vit import LOGICAL_RULES, ViT
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.training.pjit_step import (
    create_sharded_train_state,
    logical_shardings,
    make_pjit_eval_step,
    make_pjit_train_step,
)

CFG = TrainConfig(
    num_classes=10,
    image_size=16,
    batch_size_per_device=2,
    weight_decay=1e-4,
    compute_dtype="float32",
)


def _vit():
    return ViT(variant="ti", patch_size=16, num_classes=10, dtype=jnp.float32)


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return (
        rng.randn(n, 16, 16, 3).astype(np.float32),
        rng.randint(0, 10, size=(n,)).astype(np.int32),
    )


@pytest.fixture(scope="module")
def tp_mesh(devices):
    return create_mesh(axes=("data", "model"), shape=(2, 4))


def test_logical_shardings_shard_model_axes(tp_mesh):
    _, shardings = logical_shardings(_vit(), tp_mesh, LOGICAL_RULES, (1, 16, 16, 3))
    qkv = shardings["block0"]["attn"]["qkv"]["kernel"].spec
    proj = shardings["block0"]["attn"]["proj"]["kernel"].spec
    fc1 = shardings["block0"]["mlp"]["fc1"]["kernel"].spec
    assert tuple(qkv) == (None, "model")  # column-parallel
    assert tuple(proj) == ("model", None)  # row-parallel
    assert tuple(fc1) == (None, "model")
    ln = shardings["block0"]["ln1"]["scale"].spec
    assert tuple(ln) == ()  # replicated


def test_state_params_and_opt_state_sharded(tp_mesh):
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_sharded_train_state(
        _vit(), CFG, tx, tp_mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    assert tuple(qkv.sharding.spec) == (None, "model")
    # every momentum leaf mirroring a sharded param must share its sharding
    qkv_moms = [
        leaf
        for leaf in jax.tree.leaves(state.opt_state)
        if getattr(leaf, "shape", None) == qkv.shape
    ]
    assert qkv_moms
    for leaf in qkv_moms:
        assert tuple(leaf.sharding.spec) == (None, "model")


def test_tp_step_matches_single_device(tp_mesh):
    model = _vit()
    tx = optax.sgd(0.1, momentum=0.9)
    images, labels = _batch()

    state_tp = create_sharded_train_state(
        model, CFG, tx, tp_mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    step_tp = make_pjit_train_step(model, tx, tp_mesh, CFG, donate_state=False)
    with tp_mesh:
        s_tp, m_tp = step_tp(state_tp, shard_batch((images, labels), tp_mesh))

    mesh1 = create_mesh(devices=jax.devices()[:1])
    state1 = create_sharded_train_state(
        model, CFG, tx, mesh1, input_shape=(1, 16, 16, 3)
    )
    step1 = make_pjit_train_step(model, tx, mesh1, CFG, donate_state=False)
    with mesh1:
        s1, m1 = step1(state1, shard_batch((images, labels), mesh1))

    np.testing.assert_allclose(float(m_tp["loss"]), float(m1["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s_tp.params)):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(a)),
            np.asarray(jax.device_get(b)),
            atol=2e-5,
        )


def test_pjit_loss_decreases(tp_mesh):
    model = _vit()
    tx = optax.sgd(0.05)
    state = create_sharded_train_state(
        model, CFG, tx, tp_mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    step = make_pjit_train_step(model, tx, tp_mesh, CFG, donate_state=False)
    with tp_mesh:
        batch = shard_batch(_batch(), tp_mesh)
        losses = []
        for _ in range(6):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_pjit_eval_step(tp_mesh):
    model = _vit()
    tx = optax.sgd(0.05)
    state = create_sharded_train_state(
        model, CFG, tx, tp_mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    eval_step = make_pjit_eval_step(model, tp_mesh)
    with tp_mesh:
        m = eval_step(state, shard_batch(_batch(), tp_mesh))
    for key in ("loss", "top1", "top5"):
        assert np.isfinite(float(m[key]))
    assert float(m["count"]) == 16.0
    # exact-eval contract: zero-weight (padded) samples are masked out
    images, labels = _batch()
    weights = np.array([1.0] * 12 + [0.0] * 4, np.float32)
    with tp_mesh:
        mw = eval_step(state, shard_batch((images, labels, weights), tp_mesh))
    assert float(mw["count"]) == 12.0
    for key in ("loss", "top1", "top5"):
        assert np.isfinite(float(mw[key]))


def test_unannotated_model_trains_under_pjit(mesh8):
    """ResNet (no logical annotations) falls back to replicated params —
    the pjit engine is a strict superset of DP."""
    model = ResNet(depth=18, num_classes=10, dtype=jnp.float32)
    tx = optax.sgd(0.05)
    state = create_sharded_train_state(
        model, CFG, tx, mesh8, input_shape=(1, 16, 16, 3)
    )
    step = make_pjit_train_step(model, tx, mesh8, CFG, donate_state=False)
    with mesh8:
        state, metrics = step(state, shard_batch(_batch(), mesh8))
    assert int(jax.device_get(state.step)) == 1
    assert np.isfinite(float(metrics["loss"]))


def test_pjit_tp_lm_trains(tp_mesh):
    """TP x DP for the LM under the GSPMD engine: heads/mlp sharded over
    'model', tied vocab embedding replicated, one step trains."""
    from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM

    vocab, t = 32, 16
    model = TransformerLM(
        variant="tiny", vocab_size=vocab, max_seq_len=t, dtype=jnp.float32
    )
    cfg = CFG.replace(num_classes=vocab)
    tx = optax.sgd(0.2)
    state = create_sharded_train_state(
        model, cfg, tx, tp_mesh, LOGICAL_RULES,
        input_shape=(1, t), input_dtype=jnp.int32,
    )
    # the qkv kernel is genuinely sharded over the model axis
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    assert "model" in getattr(qkv.sharding, "spec", ())
    rng = np.random.RandomState(0)
    rows = rng.randint(0, vocab, size=(4, t + 1)).astype(np.int32)
    step = make_pjit_train_step(model, tx, tp_mesh, cfg, donate_state=False)
    with tp_mesh:
        batch = shard_batch((rows[:, :-1], rows[:, 1:]), tp_mesh)
        losses = []
        s = state
        for _ in range(3):
            s, metrics = step(s, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_keras_frontend_with_pjit_engine(tp_mesh):
    """TP reachable end-to-end: Model(..., engine='pjit') on a
    (data, model) mesh trains ViT with genuinely sharded params and
    evaluates through the pjit eval step."""
    from distributeddeeplearning_tpu.data.synthetic import SyntheticImageDataset
    from distributeddeeplearning_tpu.frontends import Model

    cfg = CFG.replace(engine="pjit", validation=True)
    data = SyntheticImageDataset(
        length=32, global_batch_size=cfg.global_batch_size,
        image_size=16, num_classes=10, num_physical_batches=2,
    )
    val = SyntheticImageDataset(
        length=24, global_batch_size=cfg.global_batch_size,
        image_size=16, num_classes=10, num_physical_batches=2, exact=True,
    )
    m = Model(_vit(), cfg, mesh=tp_mesh)
    m.compile()
    result = m.fit(data, epochs=1, validation_data=val)
    assert np.isfinite(result.history[-1]["loss"])
    assert result.history[-1]["val_samples"] == 24.0
    qkv = m.state.params["block0"]["attn"]["qkv"]["kernel"]
    assert "model" in tuple(qkv.sharding.spec)


def test_explicit_frontend_with_pjit_engine(tp_mesh):
    from distributeddeeplearning_tpu.frontends import explicit

    cfg = CFG.replace(engine="pjit")
    pieces, state = explicit.setup(
        _vit(), cfg, mesh=tp_mesh, steps_per_epoch=2
    )
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    assert "model" in tuple(qkv.sharding.spec)
    with tp_mesh:
        batch = shard_batch(_batch(), tp_mesh)
        state, metrics = pieces.train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_pjit_evaluate_uses_pjit_eval(tp_mesh):
    """loop.evaluate must not pull a TP-sharded state through the
    shard_map step's replicated in_spec."""
    from distributeddeeplearning_tpu.data.synthetic import SyntheticImageDataset
    from distributeddeeplearning_tpu.training import loop

    cfg = CFG.replace(engine="pjit")
    tx = optax.sgd(0.05)
    state = create_sharded_train_state(
        _vit(), cfg, tx, tp_mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    val = SyntheticImageDataset(
        length=24, global_batch_size=16, image_size=16, num_classes=10,
        num_physical_batches=2, exact=True,
    )
    metrics = loop.evaluate(_vit(), cfg, val, state, mesh=tp_mesh)
    assert metrics["samples"] == 24.0
    assert np.isfinite(metrics["loss"])


def test_engine_validation_and_config_mesh(devices):
    """Unknown engine rejected everywhere; mesh_axes/mesh_shape from
    config are actually consumed; annotated-model-on-wrong-mesh errors
    clearly."""
    from distributeddeeplearning_tpu.training.loop import resolve_engine

    with pytest.raises(ValueError, match="unknown engine"):
        resolve_engine(CFG.replace(engine="gspmd"))
    # config-driven mesh (the ENGINE=pjit MESH_AXES=... env path)
    cfg = CFG.replace(
        engine="pjit", mesh_axes=("data", "model"), mesh_shape=(2, 4)
    )
    engine, mesh = resolve_engine(cfg)
    assert engine == "pjit" and mesh.shape == {"data": 2, "model": 4}
    # annotated model on a mesh without a 'model' axis: the rules project
    # onto the mesh (models/sharding.rules_for_mesh) — params degrade to
    # replicated and the run is plain DP, not an error. One rules table
    # serves every topology (model / expert / pipe axes optional).
    from distributeddeeplearning_tpu.training.pjit_step import build_pjit_state

    dp_cfg = CFG.replace(engine="pjit")  # no mesh_shape -> pure-data mesh
    _, dp_mesh = resolve_engine(dp_cfg)
    state = build_pjit_state(_vit(), dp_cfg, optax.sgd(0.1), dp_mesh)
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    # replicated, not sharded
    assert all(p is None for p in tuple(qkv.sharding.spec))


def test_estimator_frontend_with_pjit_engine(tp_mesh):
    """Third front-end x pjit engine cell: Estimator trains and evaluates
    on a (data, model) mesh with sharded params."""
    from distributeddeeplearning_tpu.data.synthetic import SyntheticImageDataset
    from distributeddeeplearning_tpu.frontends import Estimator, RunConfig

    cfg = CFG.replace(engine="pjit")

    def data(c, length=32, exact=False):
        return SyntheticImageDataset(
            length=length, global_batch_size=c.global_batch_size,
            image_size=16, num_classes=10, num_physical_batches=2,
            exact=exact,
        )

    est = Estimator(lambda c: _vit(), cfg, RunConfig(mesh=tp_mesh))
    est.train(data, epochs=1)
    assert int(jax.device_get(est.state.step)) == 2  # 32/(2*8)
    qkv = est.state.params["block0"]["attn"]["qkv"]["kernel"]
    assert "model" in tuple(qkv.sharding.spec)
    metrics = est.evaluate(lambda c: data(c, length=24, exact=True))
    assert metrics["samples"] == 24.0
    assert np.isfinite(metrics["loss"])


def test_resnet_pjit_matches_dp_engine(mesh8):
    """VERDICT r3 #4: MODEL=resnet ENGINE=pjit trains with dp-identical
    per-replica BN semantics — the round-3 refusal guard is replaced by
    this equality oracle. One full train step of ResNet18 under the pjit
    engine must match the shard_map dp engine: loss, updated params, and
    batch_stats (the BN statistics ARE the semantics under test).

    One step, not several: the stem maxpool routes gradients by argmax,
    so float-noise-level (1e-7) forward differences flip tie decisions
    and amplify discretely to O(1) param differences within two more
    steps — measured on both orderings. Multi-step equality is therefore
    not a meaningful oracle for any BN+maxpool model; the single-step
    check covers forward, backward, optimizer, and stats updates."""
    from distributeddeeplearning_tpu.training.pjit_step import (
        build_pjit_state,
    )
    from distributeddeeplearning_tpu.training.train_step import (
        create_train_state,
        make_train_step,
        replicate_state,
    )

    model = ResNet(depth=18, num_classes=10, dtype=jnp.float32)
    cfg = CFG.replace(engine="pjit", image_size=16)
    tx = optax.sgd(0.05)

    dp_state = replicate_state(
        create_train_state(model, cfg, tx, input_shape=(1, 16, 16, 3)), mesh8
    )
    dp_step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    pj_state = build_pjit_state(model, cfg, tx, mesh8)
    pj_step = make_pjit_train_step(model, tx, mesh8, cfg, donate_state=False)

    host = _batch(16, seed=0)
    dp_state, dp_metrics = dp_step(dp_state, shard_batch(host, mesh8))
    pj_state, pj_metrics = pj_step(pj_state, shard_batch(host, mesh8))

    np.testing.assert_allclose(
        float(pj_metrics["loss"]), float(dp_metrics["loss"]), rtol=1e-5
    )
    for a, b in zip(
        jax.tree.leaves(jax.device_get(pj_state.params)),
        jax.tree.leaves(jax.device_get(dp_state.params)),
    ):
        np.testing.assert_allclose(a, b, atol=2e-5)
    for a, b in zip(
        jax.tree.leaves(jax.device_get(pj_state.batch_stats)),
        jax.tree.leaves(jax.device_get(dp_state.batch_stats)),
    ):
        np.testing.assert_allclose(a, b, atol=2e-5)

    # and further steps train stably through the grouped-BN path
    for seed in (1, 2):
        pj_state, pj_metrics = pj_step(
            pj_state, shard_batch(_batch(16, seed=seed), mesh8)
        )
    assert np.isfinite(float(pj_metrics["loss"]))


def test_sync_bn_opt_in_differs_from_per_replica(mesh8):
    """ALLOW_SYNC_BN=1 really changes the statistics: global-batch BN
    must NOT equal the batch-split per-replica default (on a random
    batch the per-shard means differ from the global mean)."""
    from distributeddeeplearning_tpu.training.pjit_step import (
        build_pjit_state,
    )

    model = ResNet(depth=18, num_classes=10, dtype=jnp.float32)
    cfg = CFG.replace(engine="pjit", image_size=16)
    tx = optax.sgd(0.05)
    host = _batch(16, seed=3)

    stats = {}
    for name, sync in (("replica", False), ("sync", True)):
        c = cfg.replace(allow_sync_bn=sync)
        state = build_pjit_state(model, c, tx, mesh8)
        step = make_pjit_train_step(model, tx, mesh8, c, donate_state=False)
        state, _ = step(state, shard_batch(host, mesh8))
        stats[name] = jax.device_get(state.batch_stats)

    diffs = [
        float(np.max(np.abs(a - b)))
        for a, b in zip(
            jax.tree.leaves(stats["replica"]), jax.tree.leaves(stats["sync"])
        )
    ]
    assert max(diffs) > 1e-6  # the variance statistics must differ
    # env spelling reaches the flag
    from distributeddeeplearning_tpu.config import TrainConfig

    assert TrainConfig.from_env({"ALLOW_SYNC_BN": "1"}).allow_sync_bn


def test_incapable_bn_models_still_refused_under_pjit(mesh8):
    """The narrowed guard: per-replica semantics only exist for models
    whose norm layers are the group-capable subclass. ResNet(fused=True)
    (in-kernel statistics) and any plain-``nn.BatchNorm`` model are
    still refused rather than silently training sync-BN."""
    import flax.linen as nn

    from distributeddeeplearning_tpu.training.pjit_step import build_pjit_state

    cfg = CFG.replace(engine="pjit", image_size=16)
    tx = optax.sgd(0.05)
    fused = ResNet(depth=50, num_classes=10, dtype=jnp.float32, fused=True)
    with pytest.raises(ValueError, match="per_replica_bn_capable"):
        build_pjit_state(fused, cfg, tx, mesh8)

    class PlainBNNet(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = nn.Conv(4, (3, 3), dtype=jnp.float32)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return nn.Dense(10)(x.mean(axis=(1, 2)))

    with pytest.raises(ValueError, match="per_replica_bn_capable"):
        build_pjit_state(PlainBNNet(), cfg, tx, mesh8)
    # sync-BN opt-in still admits both
    state = build_pjit_state(
        PlainBNNet(), cfg.replace(allow_sync_bn=True), tx, mesh8
    )
    assert state.batch_stats
    # norm-free models are unaffected
    build_pjit_state(
        _vit(), cfg.replace(image_size=CFG.image_size), tx, mesh8
    )


def test_uint8_staging_through_pjit_engine(mesh8):
    """INPUT_STAGING=uint8 composes with ENGINE=pjit: the GSPMD train
    and eval steps fold the normalize in, same as the dp engine."""
    from distributeddeeplearning_tpu.training.pjit_step import build_pjit_state

    model = ResNet(depth=18, num_classes=10, dtype=jnp.float32)
    cfg = CFG.replace(engine="pjit", image_size=16)
    tx = optax.sgd(0.05)
    state = build_pjit_state(model, cfg, tx, mesh8)
    step = make_pjit_train_step(model, tx, mesh8, cfg, donate_state=False)
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 255, size=(16, 16, 16, 3)).astype(np.uint8)
    labels = rng.randint(0, 10, size=(16,)).astype(np.int32)
    state, metrics = step(state, shard_batch((raw, labels), mesh8))
    assert np.isfinite(float(metrics["loss"]))
    ev = make_pjit_eval_step(model, mesh8, cfg)
    out = ev(state, shard_batch(
        (raw, labels, np.ones(16, np.float32)), mesh8
    ))
    assert np.isfinite(float(out["loss"])) and float(out["count"]) == 16.0
