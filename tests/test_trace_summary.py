"""Tests for scripts/trace_summary.py (previously untested — ISSUE 2).

A synthetic ``*.trace.json.gz`` stands in for a jax.profiler capture:
device-lane grouping, envelope-event skipping, TRACE_STEPS
normalisation, and the no-trace error path are all CPU-provable.
"""

import gzip
import json
import os

import pytest

from scripts.trace_summary import (
    load_events,
    main as trace_main,
    render,
    summarize_trace,
)


def _trace_data():
    """Two lanes: pid 1 is a TensorCore lane, pid 2 is host python."""
    return {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "/device:TPU:0 TensorCore"}},
            {"ph": "M", "name": "process_name", "pid": 2,
             "args": {"name": "python host"}},
            # device ops: two fusions (grouped), one copy
            {"ph": "X", "pid": 1, "name": "fusion.123", "dur": 2000},
            {"ph": "X", "pid": 1, "name": "fusion.7", "dur": 1000},
            {"ph": "X", "pid": 1, "name": "copy.1", "dur": 500},
            # envelope events must NOT count (would double their children)
            {"ph": "X", "pid": 1, "name": "jit_train_step", "dur": 99999},
            {"ph": "X", "pid": 1, "name": "Steps", "dur": 99999},
            # host-lane op must NOT count
            {"ph": "X", "pid": 2, "name": "hostop", "dur": 5000},
            # non-complete event on the device lane must NOT count
            {"ph": "B", "pid": 1, "name": "fusion.9", "dur": 7000},
        ]
    }


def _write_trace(dirpath, data, name="t.trace.json.gz"):
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, name)
    with gzip.open(path, "wt") as fh:
        json.dump(data, fh)
    return path


def test_grouped_per_op_totals(tmp_path):
    path = _write_trace(str(tmp_path), _trace_data())
    data, found = load_events(str(tmp_path))
    assert found == path
    groups, total = summarize_trace(data)
    # fusion.123 + fusion.7 group under "fusion": 3.0 ms over 2 events
    assert groups["fusion"] == [3.0, 2]
    assert groups["copy"] == [0.5, 1]
    assert "jit_train_step" not in groups and "Steps" not in groups
    assert "hostop" not in groups
    assert total == pytest.approx(3.5)


def test_trace_steps_normalisation(tmp_path, capsys, monkeypatch):
    """TRACE_STEPS divides the totals into ms/step in the rendered table."""
    _write_trace(str(tmp_path), _trace_data())
    monkeypatch.setenv("TRACE_STEPS", "2")
    trace_main([str(tmp_path)])
    out = capsys.readouterr().out
    # 3.5 ms total over 2 steps = 1.75 ms/step; fusion 3.0/2 = 1.50
    assert "1.8 ms/step over 2 steps" in out
    assert "1.50" in out
    # default render math, directly: 20 steps -> 0.15 ms/step for fusion
    groups, total = summarize_trace(_trace_data())
    table = render(groups, total, 20, "p")
    assert "0.15" in table


def test_newest_trace_wins(tmp_path):
    old = _trace_data()
    old["traceEvents"][2]["dur"] = 1  # distinguishable
    _write_trace(str(tmp_path), old, name="a.trace.json.gz")
    new_path = _write_trace(str(tmp_path), _trace_data(), name="b.trace.json.gz")
    os.utime(new_path, (2_000_000_000, 2_000_000_000))
    data, found = load_events(str(tmp_path))
    assert found == new_path
    groups, _ = summarize_trace(data)
    assert groups["fusion"] == [3.0, 2]


def test_no_trace_errors(tmp_path):
    with pytest.raises(SystemExit, match="no .*trace"):
        load_events(str(tmp_path))
