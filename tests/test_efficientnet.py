"""EfficientNet family: registry reachability + real train steps.

This is the VERDICT Weak-#1 regression suite: EfficientNet's stochastic
depth (drop-path, on by default via survival_prob=0.8) and head dropout
previously crashed make_train_step with flax InvalidRngError because no
'dropout' rng was threaded. Every test here runs with stochasticity ON.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models import available_models, get_model
from distributeddeeplearning_tpu.models.efficientnet import EfficientNet
from distributeddeeplearning_tpu.training import (
    create_train_state,
    make_eval_step,
    make_train_step,
)
from distributeddeeplearning_tpu.training.train_step import replicate_state

CFG = TrainConfig(
    model="efficientnet_b0",
    num_classes=10,
    image_size=32,
    batch_size_per_device=2,
    weight_decay=0.0,
    compute_dtype="float32",
)


def _model():
    # Defaults kept: survival_prob=0.8 => drop-path active, head dropout 0.2.
    return EfficientNet(variant="b0", num_classes=10, dtype=jnp.float32)


def _batch(global_batch=16, seed=0):
    rng = np.random.RandomState(seed)
    images = rng.randn(global_batch, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, size=(global_batch,)).astype(np.int32)
    return images, labels


def test_registry_has_efficientnet_family():
    names = available_models()
    for b in range(8):
        assert f"efficientnet_b{b}" in names
    model = get_model("efficientnet_b4", num_classes=10)
    assert isinstance(model, EfficientNet)
    assert model.variant == "b4"
    assert model.default_image_size == 380


def test_efficientnet_b0_param_count():
    # Canonical EfficientNet-B0 @1000 classes is ~5.29M params.
    model = get_model("efficientnet_b0")
    shapes = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 224, 224, 3), jnp.float32), train=False),
        jax.random.PRNGKey(0),
    )
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes["params"]))
    assert 5.0e6 < n < 5.6e6, n


def test_efficientnet_trains_with_stochastic_depth(mesh8):
    """survival_prob=0.8 default: the exact config that used to raise
    InvalidRngError on step 1."""
    model = _model()
    tx = optax.sgd(0.05)
    state = replicate_state(
        create_train_state(model, CFG, tx, input_shape=(1, 32, 32, 3)), mesh8
    )
    step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    batch = shard_batch(_batch(), mesh8)
    state, metrics = step(state, batch)
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["grad_norm"]) > 0.0


def test_efficientnet_loss_decreases(mesh8):
    # Per-device batch 8 (not 2): the deep stages run at 1x1 spatial, so
    # per-replica BN variance over a 2-sample shard collapses and gradients
    # explode — a shard-size artifact, not a model property. lr kept small
    # for swish+SE on random data.
    model = _model()
    tx = optax.sgd(0.01)
    cfg = CFG.replace(batch_size_per_device=8)
    state = replicate_state(
        create_train_state(model, cfg, tx, input_shape=(1, 32, 32, 3)), mesh8
    )
    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    batch = shard_batch(_batch(global_batch=64), mesh8)
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.3, losses


def test_efficientnet_eval_deterministic(mesh8):
    """Eval (train=False) needs no rng and is reproducible."""
    model = _model()
    tx = optax.sgd(0.05)
    state = replicate_state(
        create_train_state(model, CFG, tx, input_shape=(1, 32, 32, 3)), mesh8
    )
    eval_step = make_eval_step(model, mesh8)
    batch = shard_batch(_batch(), mesh8)
    a = float(eval_step(state, batch)["loss"])
    b = float(eval_step(state, batch)["loss"])
    assert a == b
