"""Oracles for ``ops/quant.py`` — the int8/fp8 primitives the
quantized decode tiers stand on.

What must hold (and is pinned here, CPU tier):

* **Round-trip error bounds** per dtype: symmetric int8 with per-slice
  scale ``amax/127`` reconstructs every element within half a
  quantization step (``scale / 2``) — the bound is *per slice*, from
  that slice's own scale, not a global fudge factor.
* **Per-channel vs per-tensor**: channels with wildly different
  magnitudes are exactly why the scales are per-channel — a per-tensor
  scale's error on the small channel is orders worse. The test builds
  that adversarial tensor and checks the ordering quantitatively.
* **Param-tree pass**: quantizes exactly the inference-streamed
  tensors (2-D matmul kernels per output channel, the tied embedding
  per vocab row), leaves norms/biases untouched, byte-splits honestly
  (int8 + scale itemized), and dequantizes back within the bound.
* **Determinism**: quantize → dequantize is bitwise-reproducible
  (round-half-to-even has no data races) — the property the serving
  engine's bitwise pool oracle (tests/test_serving_quant.py) builds on.
* **Full-forward logit error bound**: the weight quantization's
  end-to-end damage on a real LM forward stays small — the per-step
  logit error the serve_bench quality oracle documents (exact parity is
  mathematically unavailable under quantization; the bound is the
  contract instead, like the accum ULP note).
* **fp8 tier** (e4m3fn payload, ``SERVE_*_DTYPE=fp8``): the same scale
  contract at float rounding — per-slice round-trip bounds, extreme
  values kept finite (e4m3fn has no inf; overflow would round to NaN,
  not saturate), registry dispatch, the backend support probe, and the
  ``_qf8``-marker param-tree pass with honest byte splits.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.ops import quant as quantlib


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_roundtrip_error_bound_per_dtype(dtype):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 64) * 3.0, dtype)
    q, scale = quantlib.quantize_int8(x, axis=-1)
    assert q.dtype == jnp.int8 and scale.dtype == jnp.float32
    assert q.shape == x.shape and scale.shape == (16, 1)
    dq = quantlib.dequantize_int8(q, scale, jnp.float32)
    # |x - dq| <= scale/2 per slice: round() lands within half a step.
    # bf16 inputs are exact f32 values, so the same bound applies.
    err = np.abs(np.asarray(x, np.float32) - np.asarray(dq))
    bound = np.asarray(scale)[..., 0] / 2 + 1e-7
    assert (err.max(axis=-1) <= bound).all()


def test_quantize_handles_zero_slices_and_extremes():
    x = jnp.zeros((4, 8), jnp.float32)
    q, scale = quantlib.quantize_int8(x, axis=-1)
    assert np.asarray(q).max() == 0
    dq = quantlib.dequantize_int8(q, scale)
    assert np.array_equal(np.asarray(dq), np.zeros((4, 8), np.float32))
    # the amax element maps exactly onto ±127 (symmetric range)
    y = jnp.asarray([[1.0, -2.0, 0.5, 2.0]], jnp.float32)
    qy, sy = quantlib.quantize_int8(y, axis=-1)
    assert np.asarray(qy).min() == -127 and np.asarray(qy).max() == 127


def test_per_channel_beats_per_tensor_on_mixed_magnitudes():
    rng = np.random.RandomState(1)
    # channel 0 ~ O(100), channel 1 ~ O(0.01): a shared scale burns
    # the small channel's precision
    x = np.stack([rng.randn(256) * 100.0, rng.randn(256) * 0.01])
    xj = jnp.asarray(x, jnp.float32)
    q_pc, s_pc = quantlib.quantize_int8(xj, axis=-1)      # per channel
    q_pt, s_pt = quantlib.quantize_int8(xj, axis=(0, 1))  # per tensor
    assert s_pc.shape == (2, 1) and s_pt.shape == (1, 1)
    err_pc = np.abs(x[1] - np.asarray(
        quantlib.dequantize_int8(q_pc, s_pc))[1])
    err_pt = np.abs(x[1] - np.asarray(
        quantlib.dequantize_int8(q_pt, s_pt))[1])
    # per-tensor error on the small channel is ~scale_big/scale_small
    # worse; 100x margin keeps the assertion far from flakiness
    assert err_pt.max() > 100 * max(err_pc.max(), 1e-9)


def test_quantize_deterministic_bitwise():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(32, 48), jnp.float32)
    q1, s1 = quantlib.quantize_int8(x, axis=-1)
    q2, s2 = quantlib.quantize_int8(x, axis=-1)
    assert np.array_equal(np.asarray(q1), np.asarray(q2))
    assert np.array_equal(np.asarray(s1), np.asarray(s2))


@pytest.fixture(scope="module")
def lm_and_params():
    import flax.linen as nn

    from distributeddeeplearning_tpu.models.transformer_lm import (
        TransformerLM,
    )

    model = TransformerLM(
        variant="tiny", vocab_size=256, max_seq_len=32, dtype=jnp.float32
    )
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, 32), jnp.int32), train=False
    )
    return model, nn.unbox(variables["params"])


def test_param_tree_pass_structure_and_bytes(lm_and_params):
    from flax import traverse_util

    _, params = lm_and_params
    qtree = quantlib.quantize_params(params)
    assert quantlib.is_quantized(qtree)
    assert not quantlib.is_quantized(params)
    flat_in = traverse_util.flatten_dict(params)
    flat_q = traverse_util.flatten_dict(qtree)
    for path, leaf in flat_in.items():
        if quantlib._is_quantizable(path, leaf):
            q = flat_q[path + (quantlib.Q8,)]
            s = flat_q[path + (quantlib.Q8_SCALE,)]
            assert q.dtype == jnp.int8 and q.shape == leaf.shape
            assert s.dtype == jnp.float32
            # per-OUTPUT-channel for kernels, per-vocab-row for embed
            if path[-1] == "kernel":
                assert s.shape == (1, leaf.shape[1])
            else:
                assert s.shape == (leaf.shape[0], 1)
        else:
            # norms / biases / pos tables untouched, bit for bit
            assert np.array_equal(
                np.asarray(flat_q[path]), np.asarray(leaf)
            )
    split = quantlib.tree_byte_split(qtree)
    native = quantlib.tree_byte_split(params)
    assert split["int8"] > 0 and split["scale"] > 0
    # f32 -> int8 on the quantized leaves: payload is a quarter
    assert split["int8"] * 4 + split["other"] <= native["other"]
    # scales are itemized small change, not a hidden second payload
    assert split["scale"] < split["int8"] / 8


def test_param_tree_roundtrip_and_eval_shape(lm_and_params):
    from flax import traverse_util

    _, params = lm_and_params
    dq = quantlib.dequantize_params(quantlib.quantize_params(params))
    flat_in = traverse_util.flatten_dict(params)
    flat_dq = traverse_util.flatten_dict(dq)
    assert set(flat_in) == set(flat_dq)
    for path, leaf in flat_in.items():
        got = flat_dq[path]
        assert got.shape == leaf.shape
        if quantlib._is_quantizable(path, leaf):
            rel = np.abs(np.asarray(got) - np.asarray(leaf)).max()
            amax = np.abs(np.asarray(leaf)).max()
            assert rel <= amax / 127  # half-step bound, loosened to 1 step
    # the audit's shape-only path: eval_shape must run the pass without
    # materializing anything
    shapes = jax.eval_shape(quantlib.quantize_params, params)
    assert quantlib.tree_byte_split(shapes) == quantlib.tree_byte_split(
        quantlib.quantize_params(params)
    )
    # one-shot invariant: re-quantizing an already-quantized tree would
    # re-scale the int8 payload into garbage — rejected loudly (the
    # speculative tier's int8-draft-of-int8-target conflict rule guards
    # the serving-side path; this pins the pass itself)
    with pytest.raises(ValueError, match="already quantized"):
        quantlib.quantize_params(quantlib.quantize_params(params))


def test_full_forward_logit_error_bound(lm_and_params):
    """Weight quantization's end-to-end per-step logit damage on a real
    LM forward stays within a documented bound. The bound (0.05 at this
    size) is what makes the serve_bench match-rate oracle meaningful:
    errors this small flip an argmax only when the top-2 gap is
    comparably tiny."""
    model, params = lm_and_params
    dq = quantlib.dequantize_params(quantlib.quantize_params(params))
    toks = jnp.asarray(
        np.random.RandomState(3).randint(0, 256, size=(2, 24)), jnp.int32
    )
    ref = model.apply({"params": params}, toks, train=False)
    got = model.apply({"params": dq}, toks, train=False)
    err = float(jnp.max(jnp.abs(
        ref.astype(jnp.float32) - got.astype(jnp.float32)
    )))
    assert err < 0.05


# ---------------------------------------------------------------------------
# fp8 tier (e4m3 payload, same scale contract as int8)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fp8_roundtrip_error_bound_per_dtype(dtype):
    """e4m3fn carries 3 mantissa bits: after the amax/448 scaling every
    normal value reconstructs within 2^-4 relative; near-zero values
    within half a subnormal step of the scaled grid. The bound is per
    element from the slice's own scale — same shape contract as int8."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(16, 64) * 3.0, dtype)
    q, scale = quantlib.quantize_fp8(x, axis=-1)
    assert q.dtype == jnp.float8_e4m3fn and scale.dtype == jnp.float32
    assert q.shape == x.shape and scale.shape == (16, 1)
    dq = quantlib.dequantize_fp8(q, scale, jnp.float32)
    xf = np.asarray(x, np.float32)
    err = np.abs(xf - np.asarray(dq))
    sc = np.asarray(scale)
    bound = np.maximum(np.abs(xf) * 2.0 ** -4, sc * 2.0 ** -10) + 1e-9
    assert (err <= bound).all()


def test_fp8_extreme_values_stay_finite_and_exact():
    # all-zero slices: scale 1, exact zero reconstruction (no NaN)
    z = jnp.zeros((4, 8), jnp.float32)
    qz, sz = quantlib.quantize_fp8(z, axis=-1)
    assert np.array_equal(np.asarray(sz), np.ones((4, 1), np.float32))
    assert np.array_equal(
        np.asarray(quantlib.dequantize_fp8(qz, sz)),
        np.zeros((4, 8), np.float32),
    )
    # the amax element maps exactly onto ±fmax (448 for e4m3fn) and
    # reconstructs exactly; e4m3fn has no inf, so the pre-clip is what
    # keeps an overflow from rounding to NaN
    y = jnp.asarray([[1e30, -1e30, 1e-30, 0.25]], jnp.float32)
    qy, sy = quantlib.quantize_fp8(y, axis=-1)
    qf = np.asarray(qy, np.float32)
    assert np.isfinite(qf).all()
    fmax = float(jnp.finfo(jnp.float8_e4m3fn).max)
    assert qf.max() == fmax and qf.min() == -fmax
    dy = np.asarray(quantlib.dequantize_fp8(qy, sy))
    assert np.isfinite(dy).all()
    np.testing.assert_allclose(dy[0, 0], 1e30, rtol=1e-6)
    # e5m2 (the wider-exponent KV option) honors the same contract
    q5, s5 = quantlib.quantize_fp8(y, axis=-1, dtype=jnp.float8_e5m2)
    assert q5.dtype == jnp.float8_e5m2
    assert np.isfinite(np.asarray(q5, np.float32)).all()


def test_fp8_registry_dispatch_and_support_probe():
    assert quantlib.kv_store_dtype("fp8") == quantlib.FP8_KV_DTYPE
    assert quantlib.kv_store_dtype("int8") == jnp.int8
    assert quantlib.kv_store_dtype("bf16") is None
    q, s = quantlib.quantize_kv(jnp.ones((2, 4)), "fp8")
    assert q.dtype == quantlib.FP8_KV_DTYPE
    with pytest.raises(ValueError, match="kv_dtype"):
        quantlib.validate_store_dtype("kv_dtype", "int4")
    # CPU executes fp8 casts: the probe must say so (the TPU-gated
    # fallback path is exercised by monkeypatching in serving tests)
    assert quantlib.fp8_supported() is True


def test_param_tree_fp8_pass_markers_and_bytes(lm_and_params):
    model, params = lm_and_params
    qtree = quantlib.quantize_params(params, dtype="fp8")
    from flax import traverse_util

    flat = traverse_util.flatten_dict(qtree)
    markers = {p[-1] for p in flat}
    assert quantlib.QF8 in markers and quantlib.QF8_SCALE in markers
    assert quantlib.Q8 not in markers
    assert quantlib.is_quantized(qtree)
    split = quantlib.tree_byte_split(qtree)
    native = quantlib.tree_byte_split(params)
    assert split["fp8"] > 0 and split["int8"] == 0
    assert quantlib.quantized_bytes(split) == split["fp8"]
    # payload + scales + passthrough strictly below the f32 original
    assert sum(split.values()) < sum(native.values())
    # mixing tiers is still one-shot
    with pytest.raises(ValueError, match="already quantized"):
        quantlib.quantize_params(qtree, dtype="fp8")
    # dequant restores every leaf's shape; per-slice error bound holds
    dq = quantlib.dequantize_params(qtree)
    dflat = traverse_util.flatten_dict(dq)
    pflat = traverse_util.flatten_dict(params)
    assert set(dflat) == set(pflat)
    for path, leaf in pflat.items():
        if not quantlib._is_quantizable(path, leaf):
            continue
        axis = quantlib._quant_axis(path)
        ref = np.asarray(leaf, np.float32)
        got = np.asarray(dflat[path], np.float32)
        amax = np.abs(ref).max(axis=axis, keepdims=True)
        assert (np.abs(ref - got) <= amax * 2.0 ** -4 + 1e-9).all(), path
