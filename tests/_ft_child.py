"""Training child for the preemption / resume-equivalence oracles.

Every rank of a ``launch.py`` world runs this: initialise the
distributed backend, train ``loop.fit`` entirely from the env contract
(MODEL/ENGINE/EPOCHS/MODEL_DIR/CHECKPOINT_EVERY_STEPS/FAULT_PLAN/...),
then print a SHA-256 over the final parameters —
``FT_PARAMS_SHA <rank> <hexdigest>`` — so the test can assert that a
run killed mid-epoch and resumed by the restart supervisor ends
bitwise-identical to an uninterrupted one (the ISSUE 4 acceptance
criterion, riding the repo's determinism contract).
"""

import hashlib
import sys

from distributeddeeplearning_tpu.parallel import distributed


def main() -> None:
    distributed.maybe_initialize()

    import jax
    import numpy as np

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data import make_dataset
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    config = TrainConfig.from_env()
    model = get_model(config.model, **config.model_kwargs())
    result = loop.fit(
        model, config, make_dataset(config, train=True),
        add_default_logger=False,
    )

    # Bitwise param fingerprint. Params are replicated over the mesh in
    # these oracles (dp engine; pjit on a data-only mesh), so the first
    # addressable shard IS the full value on every process.
    host_params = jax.tree.map(
        lambda a: np.asarray(a.addressable_data(0)), result.state.params
    )
    digest = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves_with_path(host_params)
    for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
        digest.update(str(path).encode())
        digest.update(np.ascontiguousarray(leaf).tobytes())
    print(
        f"FT_PARAMS_SHA {jax.process_index()} {digest.hexdigest()}",
        flush=True,
    )


if __name__ == "__main__":
    sys.exit(main())
