"""Training child for the preemption / resume-equivalence oracles.

Every rank of a ``launch.py`` world runs this: initialise the
distributed backend, train ``loop.fit`` entirely from the env contract
(MODEL/ENGINE/EPOCHS/MODEL_DIR/CHECKPOINT_EVERY_STEPS/FAULT_PLAN/...),
then print a SHA-256 over the final parameters —
``FT_PARAMS_SHA <rank> <hexdigest>`` — so the test can assert that a
run killed mid-epoch and resumed by the restart supervisor ends
bitwise-identical to an uninterrupted one (the ISSUE 4 acceptance
criterion, riding the repo's determinism contract).
"""

import hashlib
import os
import sys

from distributeddeeplearning_tpu.parallel import distributed


def main() -> None:
    distributed.maybe_initialize()

    import jax
    import numpy as np

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data import make_dataset
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    config = TrainConfig.from_env()
    if config.model.startswith("lm"):
        # Token models (the elastic oracles use lm_tiny: no BatchNorm,
        # so the shrink-with-accum-rescale trajectory is provably
        # ULP-equal; BN's rsqrt amplifies reassociation noise past any
        # useful bound): the same synthetic contract, token edition.
        from distributeddeeplearning_tpu.data.synthetic import (
            SyntheticTokenDataset,
        )

        import jax as _jax

        data = SyntheticTokenDataset(
            length=config.fake_data_length,
            global_batch_size=config.global_batch_size,
            seq_len=int(os.environ.get("SEQ_LEN", "16")),
            vocab_size=config.num_classes,
            seed=config.seed,
            process_index=_jax.process_index(),
            process_count=_jax.process_count(),
            topology=config.data_topology,
        )
        model = get_model(
            config.model,
            num_classes=config.num_classes,
            dtype=config.compute_dtype,
            max_seq_len=data.seq_len,
        )
    else:
        data = make_dataset(config, train=True)
        model = get_model(config.model, **config.model_kwargs())
    result = loop.fit(
        model, config, data, add_default_logger=False,
    )

    # Loss trajectory (hex floats: exact, greppable) — the elastic
    # oracles compare the post-resume trajectory of a shrunken world
    # against an uninterrupted fixed-world run at f32-ULP tolerance.
    for h in result.history:
        if "loss" in h:
            print(
                f"FT_EPOCH_LOSS {jax.process_index()} "
                f"{int(h['global_step'])} {float(h['loss']).hex()}",
                flush=True,
            )

    # Bitwise param fingerprint. Params are replicated over the mesh in
    # these oracles (dp engine; pjit on a data-only mesh), so the first
    # addressable shard IS the full value on every process.
    host_params = jax.tree.map(
        lambda a: np.asarray(a.addressable_data(0)), result.state.params
    )
    if os.environ.get("FT_PARAMS_OUT") and jax.process_index() == 0:
        # Numeric dump for the ULP-tolerance oracles (an elastic
        # shrink's accum rescale re-associates reductions, so the
        # trajectory is f32-ULP-close, not bitwise — the SHA below
        # serves the bitwise fixed-world oracles).
        leaves = jax.tree_util.tree_leaves_with_path(host_params)
        np.savez(
            os.environ["FT_PARAMS_OUT"],
            **{str(path): leaf for path, leaf in leaves},
        )
    digest = hashlib.sha256()
    leaves = jax.tree_util.tree_leaves_with_path(host_params)
    for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
        digest.update(str(path).encode())
        digest.update(np.ascontiguousarray(leaf).tobytes())
    print(
        f"FT_PARAMS_SHA {jax.process_index()} {digest.hexdigest()}",
        flush=True,
    )


if __name__ == "__main__":
    sys.exit(main())
