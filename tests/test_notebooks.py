"""The notebook tier stays executable (VERDICT r3 #6).

``make notebooks`` (scripts/run_notebooks.py) is the full proof — it
executes all three and rewrites them with outputs. In the test tier:
the orchestration notebook executes end-to-end here (its dry-run CLIs
are fast); the two training notebooks run real multi-minute CPU-mesh
smokes, so the suite instead pins that their committed copies CARRY
executed outputs — a stale or never-executed notebook fails.
"""

import glob
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_provision_notebook_executes_headlessly(tmp_path):
    import shutil

    from scripts.run_notebooks import run_notebook

    src = os.path.join(REPO, "notebooks", "01_ProvisionAndTrain.ipynb")
    dst = tmp_path / "01.ipynb"
    shutil.copy(src, dst)
    run_notebook(str(dst), timeout=600)  # raises on any cell error
    nb = json.load(open(dst))
    codes = [c for c in nb["cells"] if c["cell_type"] == "code"]
    assert codes and all(c["execution_count"] is not None for c in codes)


@pytest.mark.parametrize(
    "name",
    ["00_BuildImageAndSmoke", "01_ProvisionAndTrain", "02_TrainFrontends"],
)
def test_committed_notebooks_carry_outputs(name):
    """Every committed notebook must be the executed artifact: each code
    cell has an execution_count and at least one cell produced output
    (``make notebooks`` regenerates them)."""
    path = os.path.join(REPO, "notebooks", f"{name}.ipynb")
    nb = json.load(open(path))
    codes = [c for c in nb["cells"] if c["cell_type"] == "code"]
    assert codes, f"{name}: no code cells"
    missing = [i for i, c in enumerate(codes) if c["execution_count"] is None]
    assert not missing, (
        f"{name}: cells {missing} were never executed — run `make notebooks`"
    )
    assert any(c["outputs"] for c in codes), f"{name}: no outputs captured"


def test_runner_covers_every_notebook():
    from scripts.run_notebooks import NOTEBOOKS

    on_disk = sorted(
        os.path.relpath(p, REPO)
        for p in glob.glob(os.path.join(REPO, "notebooks", "*.ipynb"))
    )
    assert on_disk == sorted(NOTEBOOKS)
