"""ddlint oracles (distributeddeeplearning_tpu/analysis/ — docs/ANALYSIS.md).

Two claims, both pinned here:

1. **Each rule flags its fixture** — a known sync leak, a tracer-bool
   leak, a missing donation, a collective inside a scan body, an
   undocumented env read, an unregistered gauge, a protocol knob the
   scrub list misses. A rule that can't catch its own planted violation
   is decoration.
2. **Self-hosting** — the fast families (AST + contracts) run on the
   real package and return ZERO unsuppressed findings, so `make lint`
   stays green at HEAD and a regression is attributable to the change
   that introduced it. (The HLO family self-hosts through `make lint` /
   `make check`; its fixtures here use 1-device programs.)
"""

import textwrap

import numpy as np
import pytest

from distributeddeeplearning_tpu.analysis import (
    Finding,
    apply_suppressions,
    package_sources,
    parse_suppressions,
)
from distributeddeeplearning_tpu.analysis import contracts
from distributeddeeplearning_tpu.analysis import hlo_audit
from distributeddeeplearning_tpu.analysis.ast_sync import (
    HOT_PATHS,
    lint_source,
)


def _lint(src: str):
    return lint_source(textwrap.dedent(src), "fixture.py")


# -- AST family: host-sync ------------------------------------------------


def test_float_on_traced_value_flagged():
    findings = _lint("""
        import jax.numpy as jnp

        def step(batch):
            loss = jnp.mean(batch)
            return float(loss)  # the classic leak
    """)
    assert [f.rule for f in findings] == ["host-sync"]
    assert findings[0].line == 6


def test_item_and_np_asarray_on_traced_flagged():
    findings = _lint("""
        import jax.numpy as jnp
        import numpy as np

        def step(x):
            y = jnp.sum(x)
            a = y.item()
            b = np.asarray(y * 2)
            return a, b
    """)
    assert [f.rule for f in findings] == ["host-sync", "host-sync"]


def test_raw_device_get_and_block_until_ready_flagged():
    findings = _lint("""
        import jax

        def epoch_end(metrics, x):
            host = jax.device_get(metrics)
            x.block_until_ready()
            return host
    """)
    assert sorted(f.rule for f in findings) == ["host-sync", "host-sync"]


def test_tracer_bool_fixture_flagged():
    findings = _lint("""
        import jax.numpy as jnp

        def guard(x):
            mask = jnp.isfinite(x)
            if jnp.any(mask):
                return x
            while mask:
                pass
    """)
    assert [f.rule for f in findings] == ["tracer-bool", "tracer-bool"]


def test_hostsync_allowlist_and_metadata_not_flagged():
    findings = _lint("""
        import jax.numpy as jnp
        from distributeddeeplearning_tpu.utils import hostsync

        def epoch_end(acc, cfg):
            dev = jnp.mean(acc)
            host = hostsync.device_get(dev, label="epoch")  # accounted
            v = float(host)                  # host value: fine
            n = int(dev.shape[0])            # metadata: fine
            k = float(cfg.label_smoothing)   # config float: fine
            if jnp.ndim(dev) == 0:           # jnp.ndim is host: fine
                return v, n, k
    """)
    assert findings == []


def test_jax_tree_leaves_truthiness_not_flagged():
    findings = _lint("""
        import jax

        def place(params):
            leaves = jax.tree.leaves(params)
            if leaves and len(leaves) > 2:
                return leaves
    """)
    assert findings == []


# -- suppressions ---------------------------------------------------------


def test_suppression_marks_and_counts():
    src = textwrap.dedent("""
        import jax.numpy as jnp

        def step(batch):
            loss = jnp.mean(batch)
            return float(loss)  # ddlint: ok(host-sync): boundary sync, measured
    """)
    findings = lint_source(src, "fix.py")
    assert len(findings) == 1
    out = apply_suppressions(findings, {"fix.py": src})
    assert out[0].suppressed and "measured" in out[0].reason


def test_suppression_binds_to_wrapped_statement_tail():
    src = textwrap.dedent("""
        import jax.numpy as jnp

        def step(batch):
            loss = jnp.mean(batch)
            return float(
                loss
            )  # ddlint: ok(host-sync): tail-of-statement marker
    """)
    out = apply_suppressions(lint_source(src, "fix.py"), {"fix.py": src})
    assert [f.suppressed for f in out] == [True]


def test_reasonless_suppression_is_a_finding():
    src = "x = 1  # ddlint: ok(host-sync)\n"
    by_line, malformed = parse_suppressions(src)
    assert by_line == {} and len(malformed) == 1
    out = apply_suppressions([], {"fix.py": src})
    assert [f.rule for f in out] == ["bad-suppression"]


def test_wrong_rule_suppression_does_not_apply():
    src = textwrap.dedent("""
        import jax.numpy as jnp

        def step(batch):
            loss = jnp.mean(batch)
            return float(loss)  # ddlint: ok(tracer-bool): wrong rule named
    """)
    out = apply_suppressions(lint_source(src, "fix.py"), {"fix.py": src})
    assert [f.suppressed for f in out] == [False]


# -- contracts: env-docs --------------------------------------------------


def test_env_reads_extraction_covers_all_idioms():
    src = textwrap.dedent("""
        import os

        def from_env(env=None):
            e = os.environ if env is None else env
            a = os.environ.get("VAR_A", "1")
            b = os.getenv("VAR_B")
            c = os.environ["VAR_C"]
            d = e.get("VAR_D")
            if "VAR_E" in e:
                pass
            return a, b, c, d
    """)
    names = {v for v, _ in contracts.env_reads(src)}
    assert names == {"VAR_A", "VAR_B", "VAR_C", "VAR_D", "VAR_E"}


def test_undocumented_env_read_fixture():
    documented = contracts.documented_env_vars()
    assert "OBS_DIR" in documented  # the real contract is in the docs
    assert "DDL_TOTALLY_UNDOCUMENTED_KNOB" not in documented


def test_env_docs_self_hosting():
    open_findings = [f for f in contracts.run_env_docs() if not f.suppressed]
    out = apply_suppressions(open_findings, package_sources())
    assert [f.format() for f in out if not f.suppressed] == []


# -- contracts: obs-registry ----------------------------------------------


def test_obs_emit_extraction_and_fstring_prefix():
    src = textwrap.dedent("""
        from distributeddeeplearning_tpu import obs

        def report(k, v, bus):
            obs.gauge("serve.not_a_registered_gauge", v)
            obs.counter("host_sync", 1)
            bus.gauge(f"epoch.{k}", v)
    """)
    emits = contracts.obs_emits(src)
    assert ("serve.not_a_registered_gauge", False, "gauge", 5) in emits
    assert ("epoch.", True, "gauge", 7) in emits
    registry = contracts.registered_event_names()
    assert contracts._name_registered("host_sync", False, registry)
    assert contracts._name_registered("epoch.", True, registry)
    assert not contracts._name_registered(
        "serve.not_a_registered_gauge", False, registry
    )


def test_obs_registry_self_hosting():
    out = apply_suppressions(
        contracts.run_obs_registry(), package_sources()
    )
    assert [f.format() for f in out if not f.suppressed] == []


# -- contracts: protocol-vars ---------------------------------------------


def test_recertify_tables_parse():
    scrub, rows, _ = contracts._recertify_tables()
    assert "BENCH_MODEL" in scrub and "SERVE_ADMISSION_POLICY" in scrub
    assert "resnet50" in rows and "serve_lm_chaos" in rows
    # every row's own keys are scrubbed (the in-AST half of the rule)
    for proto, keys in rows.items():
        assert keys <= scrub, (proto, keys - scrub)


def test_protocol_vars_fixture_missing_knob():
    # a SERVE_* knob nowhere in the scrub list must be caught by the
    # env-read half of the rule (simulated against the parsed tables)
    scrub, _, _ = contracts._recertify_tables()
    assert "SERVE_NOT_A_REAL_KNOB" not in scrub
    src = 'import os\nx = os.environ.get("SERVE_NOT_A_REAL_KNOB")\n'
    reads = contracts.env_reads(src)
    assert reads == [("SERVE_NOT_A_REAL_KNOB", 2)]


def test_protocol_vars_self_hosting_with_counted_suppressions():
    out = apply_suppressions(
        contracts.run_protocol_vars(), package_sources()
    )
    open_f = [f for f in out if not f.suppressed]
    assert [f.format() for f in open_f] == []
    # the bench.py infra knobs are suppressed WITH reasons, and counted
    suppressed = [f for f in out if f.suppressed]
    assert len(suppressed) >= 4
    assert all(f.reason for f in suppressed)


# -- HLO family fixtures (1-device / test-mesh programs) -------------------


def test_donation_fixture_missing_vs_delivered():
    import jax
    import jax.numpy as jnp

    def bump(state, x):
        return {"w": state["w"] + x}

    def fresh():
        return {"w": jax.device_put(jnp.zeros((64, 64), jnp.float32))}

    x = np.float32(1.0)
    state = fresh()
    donated = jax.jit(bump, donate_argnums=(0,)).lower(state, x).compile()
    assert hlo_audit.check_donation(
        donated, (state, x), (0,), "fixture donated", "fix.py"
    ) == []

    state2 = fresh()
    undonated = jax.jit(bump).lower(state2, x).compile()
    findings = hlo_audit.check_donation(
        undonated, (state2, x), (0,), "fixture undonated", "fix.py"
    )
    assert [f.rule for f in findings] == ["hlo-donation"]
    assert "fixture undonated" in findings[0].message


def test_scan_collective_placement_fixture(mesh8):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def inside(state, batch):  # the violation: pmean per microbatch
        def body(carry, mb):
            g = lax.pmean(jnp.sum(mb * state["w"]), "data")
            return carry + g, g

        tot, _ = lax.scan(body, jnp.float32(0), batch.reshape(2, -1))
        return {"w": state["w"] - tot}

    def outside(state, batch):  # the design: accumulate, reduce once
        def body(carry, mb):
            return carry + jnp.sum(mb * state["w"]), mb

        tot, _ = lax.scan(body, jnp.float32(0), batch.reshape(2, -1))
        return {"w": state["w"] - lax.pmean(tot, "data")}

    def compile_(fn):
        sh = jax.shard_map(
            fn, mesh=mesh8, in_specs=(P(), P("data")), out_specs=P()
        )
        return (
            jax.jit(sh)
            .lower({"w": jnp.ones(())}, jnp.ones((8, 4)))
            .compile()
            .as_text()
        )

    good, bad = compile_(outside), compile_(inside)
    assert hlo_audit.check_scan_collectives(
        good, good, "fixture", "fix.py"
    ) == []
    findings = hlo_audit.check_scan_collectives(
        bad, good, "fixture", "fix.py"
    )
    assert findings and any(
        "INSIDE" in f.message for f in findings
    ), [f.message for f in findings]


def test_cache_key_fixture():
    assert hlo_audit.check_cache_key("same", "same", "p", "f.py") == []
    findings = hlo_audit.check_cache_key(
        "line_a\nline_b", "line_a\nline_X", "p", "f.py"
    )
    assert [f.rule for f in findings] == ["hlo-cache-key"]
    assert "line_b" in findings[0].message


def test_hlo_text_walkers_on_synthetic_module():
    text = textwrap.dedent("""\
    HloModule jit_f, is_scheduled=true

    %scan_body.1 (p: (f32[], f32[4])) -> (f32[], f32[4]) {
      %ar.1 = f32[] all-reduce(f32[] %x), replica_groups={}, to_apply=%sum.2
      ROOT %t = (f32[], f32[4]) tuple(%ar.1, %y)
    }

    %sum.2 (a: f32[], b: f32[]) -> f32[] {
      ROOT %add = f32[] add(f32[] %a, f32[] %b)
    }

    ENTRY %main.9 (arg: f32[4]) -> f32[4] {
      %w = (f32[], f32[4]) while((f32[], f32[4]) %init), condition=%cond.3, body=%scan_body.1
      ROOT %out = f32[4] get-tuple-element((f32[], f32[4]) %w), index=1
    }
    """)
    comps = hlo_audit.hlo_computations(text)
    assert set(comps) == {"scan_body.1", "sum.2", "main.9"}
    assert hlo_audit.while_body_closure(text) == {"scan_body.1", "sum.2"}
    assert hlo_audit.allreduce_sites(text) == [
        ("scan_body.1",
         "%ar.1 = f32[] all-reduce(f32[] %x), replica_groups={}, "
         "to_apply=%sum.2"),
    ]


# -- SlotEngine program-set table (the warmup/lint shared surface) ---------


def test_program_specs_match_programs_expected():
    import jax
    import jax.numpy as jnp

    import flax.linen as nn

    from distributeddeeplearning_tpu.models.transformer_lm import (
        TransformerLM,
    )
    from distributeddeeplearning_tpu.serving.engine import SlotEngine

    model = TransformerLM(
        variant="tiny", vocab_size=32, max_seq_len=8, dtype=jnp.float32
    )
    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32), train=False
    )["params"])
    for kwargs in (
        {},  # dense
        {"spec_k": 2, "spec_draft": "ngram"},  # + batched verify
    ):
        eng = SlotEngine(
            model, params, num_slots=2, max_len=8, buckets=(4, 8),
            **kwargs,
        )
        specs = eng.program_specs()
        names = [s.name for s in specs]
        assert len(names) == len(set(names))
        assert len(specs) == eng.programs_expected, (names, kwargs)
        assert names[0] == "decode"
        assert {"prefill_b4", "prefill_b8"} <= set(names)
        if kwargs.get("spec_k"):
            assert "spec_verify" in names
        # nothing is compiled by listing the table
        assert eng.compile_count == 0 and not specs[0].installed


# -- AST hot-path list stays anchored to real files ------------------------


def test_hot_paths_exist():
    import os

    from distributeddeeplearning_tpu.analysis import PACKAGE_ROOT

    for rel in HOT_PATHS:
        assert os.path.isfile(os.path.join(PACKAGE_ROOT, rel)), rel


def test_ast_rules_self_hosting():
    from distributeddeeplearning_tpu.analysis.ast_sync import (
        run_host_sync,
        run_tracer_bool,
    )

    out = apply_suppressions(
        run_host_sync() + run_tracer_bool(), package_sources()
    )
    assert [f.format() for f in out if not f.suppressed] == []
