"""Fused bottleneck-segment ops (ops/pallas/fused_block.py).

The fused path is the PROFILE.md roadmap-item-1 experiment (measured a
net LOSS on hardware — kept flag-gated off; see PROFILE.md). These tests
pin its correctness: op-level values/grads against pure-JAX references,
and block-level exact parity (params, outputs, grads, running stats)
with the standard BottleneckBlock.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.ops.pallas.fused_block import (
    bn_relu_matmul_stats,
    matmul_stats,
)


def _ref_mm(a, w):
    y = a @ w
    return y, jnp.sum(y, 0), jnp.sum(y * y, 0)


def _ref_bn(a, mean, var, scale, bias, w, eps=1e-5):
    z = jnp.maximum(
        (a - mean) * jax.lax.rsqrt(var + eps) * scale + bias, 0.0
    )
    y = z @ w
    return y, jnp.sum(y, 0), jnp.sum(y * y, 0)


def _inputs(m=70, k=16, n=24, seed=0):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(m, k).astype(np.float32)),
        jnp.asarray(rng.randn(k).astype(np.float32) * 0.1),
        jnp.asarray(np.abs(rng.randn(k)).astype(np.float32) + 0.5),
        jnp.asarray(rng.randn(k).astype(np.float32)),
        jnp.asarray(rng.randn(k).astype(np.float32) * 0.1),
        jnp.asarray(rng.randn(k, n).astype(np.float32)),
    )


def _scalar_loss(fn):
    def f(*args):
        y, s, ss = fn(*args)
        return (
            jnp.sum(y**2) + jnp.sum(jnp.sin(s)) + jnp.sum(jnp.cos(ss * 1e-2))
        )

    return f


def test_matmul_stats_values_and_grads():
    a, _, _, _, _, w = _inputs()
    for g, r in zip(matmul_stats(a, w), _ref_mm(a, w)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=2e-4)
    g_got = jax.grad(_scalar_loss(matmul_stats), argnums=(0, 1))(a, w)
    g_ref = jax.grad(_scalar_loss(_ref_mm), argnums=(0, 1))(a, w)
    for gg, gr in zip(g_got, g_ref):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gr), atol=2e-3)


def test_bn_relu_matmul_stats_values_and_grads():
    args = _inputs()
    for g, r in zip(bn_relu_matmul_stats(*args), _ref_bn(*args)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=2e-4)
    g_got = jax.grad(
        _scalar_loss(bn_relu_matmul_stats), argnums=tuple(range(6))
    )(*args)
    g_ref = jax.grad(_scalar_loss(_ref_bn), argnums=tuple(range(6)))(*args)
    for name, gg, gr in zip(
        ("a", "mean", "var", "scale", "bias", "w"), g_got, g_ref
    ):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=5e-3, err_msg=name
        )


def test_fused_block_matches_standard_block():
    """Same variable tree (paths AND init values), same forward, same
    grads, same running-stat updates, train and eval — the fused path is
    a drop-in reimplementation, checkpoint-compatible both ways."""
    from distributeddeeplearning_tpu.models.resnet import BottleneckBlock

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 16), jnp.float32) * 2
    std = BottleneckBlock(filters=8, strides=2, dtype=jnp.float32)
    fus = BottleneckBlock(filters=8, strides=2, dtype=jnp.float32, fused=True)
    v_std = std.init(jax.random.PRNGKey(2), x, train=False)
    v_fus = fus.init(jax.random.PRNGKey(2), x, train=False)
    assert jax.tree.structure(v_std) == jax.tree.structure(v_fus)
    for (p1, a), (p2, b) in zip(
        jax.tree_util.tree_leaves_with_path(v_std),
        jax.tree_util.tree_leaves_with_path(v_fus),
    ):
        assert str(p1) == str(p2) and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def loss(model):
        def f(params):
            out, mut = model.apply(
                {"params": params, "batch_stats": v_std["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return jnp.sum(out * out), mut

        return f

    (l_s, mut_s), g_s = jax.value_and_grad(loss(std), has_aux=True)(
        v_std["params"]
    )
    (l_f, mut_f), g_f = jax.value_and_grad(loss(fus), has_aux=True)(
        v_std["params"]
    )
    np.testing.assert_allclose(float(l_s), float(l_f), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_s), jax.tree.leaves(g_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)
    for a, b in zip(
        jax.tree.leaves(mut_s["batch_stats"]),
        jax.tree.leaves(mut_f["batch_stats"]),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(std.apply(v_std, x, train=False)),
        np.asarray(fus.apply(v_fus, x, train=False)),
        atol=1e-5,
    )
