"""Real-data pipeline tests on a tiny generated ImageFolder tree."""

import os

import numpy as np
import pytest

from distributeddeeplearning_tpu.data.imagenet import (
    ImageFolderDataset,
    TFRecordImageNetDataset,
)
from distributeddeeplearning_tpu.data.prepare import sort_val_images, write_tfrecords


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("imagenet")
    rng = np.random.RandomState(0)
    for cls in ("n01440764", "n01443537", "n01484850"):
        d = root / cls
        d.mkdir()
        for i in range(8):
            arr = rng.randint(0, 255, size=(40, 52, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"img_{i}.jpeg")
    return str(root)


def test_image_folder_basic(image_tree):
    ds = ImageFolderDataset(
        image_tree, global_batch_size=8, image_size=16, train=True, num_workers=2
    )
    assert ds.num_classes == 3
    assert len(ds) == 24
    assert ds.steps_per_epoch == 3
    batches = list(ds.epoch(0))
    assert len(batches) == 3
    imgs, labels = batches[0]
    assert imgs.shape == (8, 16, 16, 3)
    assert imgs.dtype == np.float32
    assert labels.min() >= 0 and labels.max() < 3
    # normalized: values roughly centered
    assert abs(float(imgs.mean())) < 3.0


def test_image_folder_eval_deterministic(image_tree):
    ds = ImageFolderDataset(
        image_tree, global_batch_size=8, image_size=16, train=False, num_workers=2
    )
    a = next(ds.epoch(0))
    b = next(ds.epoch(0))
    np.testing.assert_array_equal(a[0], b[0])


def test_image_folder_train_shuffles_by_epoch(image_tree):
    ds = ImageFolderDataset(
        image_tree, global_batch_size=8, image_size=16, train=True, num_workers=2
    )
    a = next(ds.epoch(0))
    b = next(ds.epoch(1))
    assert not np.array_equal(a[1], b[1]) or not np.array_equal(a[0], b[0])


def test_image_folder_process_sharding(image_tree):
    d0 = ImageFolderDataset(
        image_tree, global_batch_size=8, image_size=16, train=False,
        process_index=0, process_count=2, num_workers=1,
    )
    d1 = ImageFolderDataset(
        image_tree, global_batch_size=8, image_size=16, train=False,
        process_index=1, process_count=2, num_workers=1,
    )
    a = next(d0.epoch(0))
    b = next(d1.epoch(0))
    assert a[0].shape[0] == 4 and b[0].shape[0] == 4
    assert not np.array_equal(a[0], b[0])


def test_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ImageFolderDataset(str(tmp_path), global_batch_size=4)


def test_tfrecords_roundtrip(image_tree, tmp_path):
    n, classes = write_tfrecords(image_tree, str(tmp_path / "tfr"), num_shards=2)
    assert n == 24 and len(classes) == 3
    ds = TFRecordImageNetDataset(
        str(tmp_path / "tfr" / "imagenet-*"),
        global_batch_size=8,
        image_size=16,
        train=True,
    )
    assert ds.length == 24
    batches = list(ds.epoch(0))
    assert len(batches) == 3
    imgs, labels = batches[0]
    assert imgs.shape == (8, 16, 16, 3)
    assert labels.dtype == np.int32
    # eval path too: 3-tuples with weights, exact coverage
    ds_eval = TFRecordImageNetDataset(
        str(tmp_path / "tfr" / "imagenet-*"),
        global_batch_size=8,
        image_size=16,
        train=False,
        length=24,
    )
    imgs, _, w = next(iter(ds_eval.epoch(0)))
    assert imgs.shape == (8, 16, 16, 3)
    assert w.shape == (8,)


def test_tfrecord_eval_exact_coverage_nondivisible(image_tree, tmp_path):
    """24 records, global batch 7 → ceil = 4 steps; every record exactly
    once across 2 simulated processes, padding zero-weighted."""
    write_tfrecords(image_tree, str(tmp_path / "tfr"), num_shards=3)
    seen = []
    total_w = 0.0
    for p in (0, 1):
        ds = TFRecordImageNetDataset(
            str(tmp_path / "tfr" / "imagenet-*"),
            global_batch_size=14,
            image_size=16,
            train=False,
            length=24,
            process_index=p,
            process_count=2,
        )
        assert ds.steps_per_epoch == 2  # ceil(24/14)
        nb = 0
        for imgs, labels, w in ds.epoch(0):
            assert imgs.shape[0] == 7 and w.shape == (7,)
            seen.extend(labels[w > 0].tolist())
            total_w += float(w.sum())
            nb += 1
        assert nb == 2  # both processes step in lockstep
    assert total_w == 24.0  # every record weighted exactly once
    assert len(seen) == 24


def test_tfrecord_eval_lockstep_with_stale_count(image_tree, tmp_path):
    """A wrong record count (stale count.txt) must not break lockstep:
    every process still yields exactly steps_per_epoch batches."""
    write_tfrecords(image_tree, str(tmp_path / "tfr"), num_shards=2)
    for p in (0, 1):
        ds = TFRecordImageNetDataset(
            str(tmp_path / "tfr" / "imagenet-*"),
            global_batch_size=8,
            image_size=16,
            train=False,
            length=27,  # actual shards hold 24
            process_index=p,
            process_count=2,
        )
        assert ds.steps_per_epoch == 4  # ceil(27/8)
        batches = list(ds.epoch(0))
        assert len(batches) == 4  # lockstep despite the lie


def test_imagefolder_eval_exact_coverage(image_tree):
    """ImageFolder eval: ceil steps, pad+mask, each image exactly once."""
    total = 0.0
    for p in (0, 1):
        ds = ImageFolderDataset(
            image_tree,
            global_batch_size=10,
            image_size=16,
            train=False,
            process_index=p,
            process_count=2,
        )
        assert ds.steps_per_epoch == 3  # ceil(24/10)
        for _, _, w in ds.epoch(0):
            assert w.shape == (5,)
            total += float(w.sum())
    assert total == 24.0


def test_valprep(tmp_path):
    from PIL import Image

    val = tmp_path / "val"
    val.mkdir()
    for i in range(4):
        Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(
            val / f"ILSVRC2012_val_{i:08d}.JPEG"
        )
    mapping = tmp_path / "map.txt"
    mapping.write_text(
        "ILSVRC2012_val_00000000.JPEG n01\n"
        "ILSVRC2012_val_00000001.JPEG n01\n"
        "ILSVRC2012_val_00000002.JPEG n02\n"
        "ILSVRC2012_val_00000003.JPEG n02\n"
        "ILSVRC2012_val_00000099.JPEG n03\n"  # missing file: skipped
    )
    out = tmp_path / "sorted"
    moved = sort_val_images(str(val), str(mapping), str(out))
    assert moved == 4
    assert sorted(os.listdir(out)) == ["n01", "n02"]
    assert len(os.listdir(out / "n01")) == 2


def test_end_to_end_imagefolder_training(image_tree, mesh8):
    """Real-data pipeline feeds the real train step."""
    import jax.numpy as jnp
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.training import create_train_state, make_train_step
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    cfg = TrainConfig(num_classes=3, image_size=16, compute_dtype="float32")
    model = ResNet(depth=18, num_classes=3, dtype=jnp.float32)
    tx = optax.sgd(0.01)
    state = replicate_state(
        create_train_state(model, cfg, tx, input_shape=(1, 16, 16, 3)), mesh8
    )
    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    ds = ImageFolderDataset(
        image_tree, global_batch_size=8, image_size=16, train=True, num_workers=2
    )
    for images, labels in ds.epoch(0):
        state, metrics = step(state, shard_batch((images, labels), mesh8))
    assert int(state.step) == 3


def test_tfrecord_count_metadata(image_tree, tmp_path):
    write_tfrecords(image_tree, str(tmp_path / "tfr"), num_shards=2)
    assert (tmp_path / "tfr" / "count.txt").read_text().strip() == "24"
    ds = TFRecordImageNetDataset(
        str(tmp_path / "tfr" / "imagenet-*"), global_batch_size=8, image_size=16
    )
    assert ds.length == 24  # from count.txt, no scan


def test_tfrecord_equal_steps_across_uneven_processes(image_tree, tmp_path):
    # 3 shards over 2 processes: file-sharding is uneven (2 vs 1 files),
    # but both processes must yield exactly steps_per_epoch batches or a
    # pod-scale collective would deadlock.
    write_tfrecords(image_tree, str(tmp_path / "tfr3"), num_shards=3)
    counts = []
    for pi in range(2):
        ds = TFRecordImageNetDataset(
            str(tmp_path / "tfr3" / "imagenet-*"),
            global_batch_size=8,
            image_size=16,
            train=True,
            process_index=pi,
            process_count=2,
        )
        counts.append(len(list(ds.epoch(0))))
        assert ds.local_batch_size == 4
    assert counts[0] == counts[1] == ds.steps_per_epoch == 3


def test_make_dataset_tiny_fake_validation():
    # Regression: fake_data_length // 25 == 0 crashed the eval dataset.
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data import make_dataset

    cfg = TrainConfig(fake=True, fake_data_length=16, batch_size_per_device=2,
                      image_size=8, num_classes=3)
    ds = make_dataset(cfg, train=False)
    batches = list(ds.epoch(0))
    assert len(batches) >= 1


class TestNativeTFRecordDataset:
    """The TF-free reader (native index + Example codec + PIL decode)."""

    @pytest.fixture(scope="class")
    def tfr_pattern(self, image_tree, tmp_path_factory):
        out = tmp_path_factory.mktemp("native_tfr")
        write_tfrecords(image_tree, str(out), num_shards=3)
        return os.path.join(str(out), "imagenet-*")

    def test_train_epoch(self, tfr_pattern):
        from distributeddeeplearning_tpu.data.imagenet import (
            NativeTFRecordImageNetDataset,
        )

        ds = NativeTFRecordImageNetDataset(
            tfr_pattern, global_batch_size=8, image_size=16, train=True,
            num_workers=2,
        )
        assert len(ds) == 24
        assert ds.steps_per_epoch == 3
        batches = list(ds.epoch(0))
        assert len(batches) == 3
        imgs, labels = batches[0]
        assert imgs.shape == (8, 16, 16, 3)
        assert imgs.dtype == np.float32
        assert labels.dtype == np.int32
        assert labels.min() >= 0 and labels.max() < 24
        # epoch reshuffle: different epochs see different batch orderings
        b0 = list(ds.epoch(0))[0][1]
        b1 = list(ds.epoch(1))[0][1]
        assert not np.array_equal(b0, b1)

    def test_eval_exact_coverage_and_folder_parity(self, tfr_pattern, image_tree):
        from distributeddeeplearning_tpu.data.imagenet import (
            NativeTFRecordImageNetDataset,
        )

        ds = NativeTFRecordImageNetDataset(
            tfr_pattern, global_batch_size=16, image_size=16, train=False,
            num_workers=2,
        )
        assert ds.steps_per_epoch == 2  # ceil(24/16)
        batches = list(ds.epoch(0))
        weights = np.concatenate([b[2] for b in batches])
        assert weights.sum() == 24  # every record exactly once
        got = np.concatenate([b[0] for b in batches])[weights > 0]
        assert got.shape == (24, 16, 16, 3)
        # Eval decode is deterministic and shares the PIL transform with
        # ImageFolderDataset — the same 24 JPEGs must come out pixel-
        # identical (as a multiset; record order differs from file order).
        # (tf.data parity is NOT asserted: TF's JPEG decoder and resize
        # kernels legitimately differ from PIL's by a few counts/pixel.)
        folder = ImageFolderDataset(
            image_tree, global_batch_size=8, image_size=16, train=False,
            num_workers=2,
        )
        ref = np.concatenate([b[0] for b in folder.epoch(0)])

        def sig(a):
            return np.sort(a.reshape(a.shape[0], -1).sum(axis=1))

        np.testing.assert_allclose(sig(got), sig(ref), rtol=1e-5, atol=1e-5)

    def test_process_sharding_disjoint(self, tfr_pattern):
        from distributeddeeplearning_tpu.data.imagenet import (
            NativeTFRecordImageNetDataset,
        )

        seen = []
        for p in range(2):
            ds = NativeTFRecordImageNetDataset(
                tfr_pattern, global_batch_size=8, image_size=16, train=False,
                process_index=p, process_count=2, num_workers=2,
            )
            for batch in ds.epoch(0):
                seen.append((p, batch[1][batch[2] > 0]))
        labels_by_p = {
            p: np.concatenate([l for q, l in seen if q == p]) for p in (0, 1)
        }
        assert len(labels_by_p[0]) + len(labels_by_p[1]) == 24


def test_make_dataset_format_resolution(image_tree, tmp_path, monkeypatch):
    """data_format=auto sniffs TFRecord shards vs class trees; explicit
    formats are honored; DATA_FORMAT env reaches the config."""
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data import (
        _resolve_data_format,
        _tfrecord_pattern,
        make_dataset,
    )

    out = tmp_path / "shards"
    write_tfrecords(image_tree, str(out), num_shards=2)

    cfg = TrainConfig.from_env({"DATA_FORMAT": "tfrecord-native"})
    assert cfg.data_format == "tfrecord-native"
    auto = TrainConfig(data_format="auto")
    assert _resolve_data_format(auto, image_tree) == "imagefolder"
    assert _resolve_data_format(auto, str(out)) in ("tfrecord", "tfrecord-native")
    assert _tfrecord_pattern(str(out)).endswith("*-of-*")
    with pytest.raises(ValueError, match="unknown data_format"):
        _resolve_data_format(TrainConfig(data_format="parquet"), image_tree)

    cfg = TrainConfig(
        fake=False, data_dir=str(out), data_format="tfrecord-native",
        image_size=16, batch_size_per_device=1, num_workers=2,
    )
    ds = make_dataset(cfg, train=True)
    assert type(ds).__name__ == "NativeTFRecordImageNetDataset"
    assert len(ds) == 24
    cfg2 = cfg.replace(data_format="auto", data_dir=image_tree)
    ds2 = make_dataset(cfg2, train=True)
    assert type(ds2).__name__ == "ImageFolderDataset"


def test_process_workers_match_thread_workers(image_tree):
    """worker_mode='process' (the reference Keras MULTIPROCESSING knob)
    yields bit-identical batches to the thread pool — per-sample seeded
    augmentation makes decode order-independent."""
    from distributeddeeplearning_tpu.data.imagenet import ImageFolderDataset

    kw = dict(global_batch_size=4, image_size=16, train=True, num_workers=2)
    thread_ds = ImageFolderDataset(image_tree, **kw)
    proc_ds = ImageFolderDataset(image_tree, worker_mode="process", **kw)
    for (xi, yi), (xp, yp), _ in zip(thread_ds.epoch(1), proc_ds.epoch(1),
                                     range(2)):
        np.testing.assert_array_equal(xi, xp)
        np.testing.assert_array_equal(yi, yp)
    with pytest.raises(ValueError, match="worker_mode"):
        next(iter(ImageFolderDataset(image_tree, worker_mode="fork", **kw).epoch(0)))


def test_worker_mode_env_contract():
    from distributeddeeplearning_tpu.config import TrainConfig

    assert TrainConfig.from_env({"WORKER_MODE": "process"}).worker_mode == "process"
    # reference Keras spelling (imagenet_keras_horovod.py:44-46)
    assert TrainConfig.from_env({"MULTIPROCESSING": "True"}).worker_mode == "process"
    assert TrainConfig.from_env({"MULTIPROCESSING": "False"}).worker_mode == "thread"


def test_process_pool_cached_across_epochs(image_tree):
    """ADVICE r3: the spawn pool is created once per dataset and reused
    across epochs (not re-spawned per epoch), and close() shuts it down
    deterministically."""
    from distributeddeeplearning_tpu.data.imagenet import ImageFolderDataset

    ds = ImageFolderDataset(
        image_tree, worker_mode="process",
        global_batch_size=4, image_size=16, train=True, num_workers=2,
    )
    next(ds.epoch(0))
    pool0 = ds._pool
    assert pool0 is not None
    next(ds.epoch(1))
    assert ds._pool is pool0  # reused, not respawned
    ds.close()
    assert ds._pool is None
    # usable again after close: a fresh pool is built lazily
    next(ds.epoch(2))
    assert ds._pool is not None and ds._pool is not pool0
    ds.close()


def test_abandoned_epoch_local_pool_shuts_down(image_tree):
    """Thread (epoch-local) pools: abandoning the generator mid-epoch
    triggers the driver's finally-shutdown at close() time."""
    from distributeddeeplearning_tpu.data.imagenet import ImageFolderDataset

    ds = ImageFolderDataset(
        image_tree, global_batch_size=4, image_size=16, train=True,
        num_workers=2,
    )
    gen = ds.epoch(0)
    next(gen)
    gen.close()  # GeneratorExit → finally → pool.shutdown


def test_uint8_staging_matches_f32_pipeline(image_tree):
    """INPUT_STAGING=uint8 (VERDICT r3 #3): the dataset yields raw bytes
    and the device-side normalize reproduces the f32 pipeline to within
    one uint8 quantum; the dp train step accepts the uint8 batch
    directly and computes the same loss."""
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.data import staging_dtype
    from distributeddeeplearning_tpu.data.imagenet import ImageFolderDataset
    from distributeddeeplearning_tpu.data.pipeline import (
        normalize_staged_images,
    )
    from distributeddeeplearning_tpu.config import TrainConfig

    assert staging_dtype(
        TrainConfig.from_env({"INPUT_STAGING": "uint8"})
    ) == np.uint8

    kw = dict(global_batch_size=4, image_size=16, train=False, num_workers=2)
    f32 = ImageFolderDataset(image_tree, **kw)
    raw = ImageFolderDataset(image_tree, image_dtype=np.uint8, **kw)
    (xf, yf, _), (xr, yr, _) = next(f32.epoch(0)), next(raw.epoch(0))
    assert xr.dtype == np.uint8
    np.testing.assert_array_equal(yf, yr)
    normalized = np.asarray(normalize_staged_images(jnp.asarray(xr)))
    # one pixel quantum (1/255) scaled by the normalization SD
    np.testing.assert_allclose(normalized, xf, atol=1.5 / 255 / 0.22)
    # non-uint8 passes through untouched
    same = normalize_staged_images(jnp.asarray(xf))
    np.testing.assert_array_equal(np.asarray(same), xf)


def test_uint8_batch_trains_through_dp_engine(image_tree, mesh8):
    import jax.numpy as jnp
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.imagenet import ImageFolderDataset
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.resnet import ResNet
    from distributeddeeplearning_tpu.training import (
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import (
        replicate_state,
    )

    cfg = TrainConfig(num_classes=3, image_size=16, batch_size_per_device=1)
    model = ResNet(depth=18, num_classes=3, dtype=jnp.float32)
    tx = optax.sgd(0.1)
    state = replicate_state(
        create_train_state(model, cfg, tx, input_shape=(1, 16, 16, 3)), mesh8
    )
    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    ds = ImageFolderDataset(
        image_tree, image_dtype=np.uint8, global_batch_size=8,
        image_size=16, train=True, num_workers=2,
    )
    images, labels = next(ds.epoch(0))
    assert images.dtype == np.uint8
    _, metrics = step(state, shard_batch((images, labels), mesh8))
    assert np.isfinite(float(metrics["loss"]))


def test_uint8_token_batches_pass_through_normalize():
    """Byte-level LMs feed uint8 TOKEN batches (rank 2) through the same
    engines — the image-normalize contract must not fire on them."""
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.data.pipeline import (
        normalize_staged_images,
    )

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 255, size=(4, 16)), jnp.uint8
    )
    out = normalize_staged_images(tokens)
    assert out.dtype == jnp.uint8
    np.testing.assert_array_equal(np.asarray(out), np.asarray(tokens))
