"""All three API front-ends drive the same engine to the same result."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.synthetic import SyntheticImageDataset
from distributeddeeplearning_tpu.frontends import Estimator, Model, RunConfig, explicit
from distributeddeeplearning_tpu.models.resnet import ResNet
from distributeddeeplearning_tpu.training.callbacks import (
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    LoggerCallback,
    MetricAverageCallback,
    ModelCheckpointCallback,
)

CFG = TrainConfig(
    num_classes=10,
    image_size=16,
    batch_size_per_device=2,
    epochs=1,
    fake_data_length=64,
    compute_dtype="float32",
    log_every_steps=2,
    validation=True,
)


def _model():
    return ResNet(depth=18, num_classes=10, dtype=jnp.float32)


def _data(cfg, length=None, **kw):
    """One construction point for the tests' synthetic datasets; ``kw``
    passes through (``exact=True`` for exact-coverage eval sets,
    ``one_hot=True`` for the categorical path)."""
    return SyntheticImageDataset(
        length=length or cfg.fake_data_length,
        global_batch_size=cfg.global_batch_size,
        image_size=cfg.image_size,
        num_classes=cfg.num_classes,
        num_physical_batches=2,
        seed=cfg.seed,
        **kw,
    )


def test_estimator_frontend(mesh8):
    est = Estimator(lambda cfg: _model(), CFG)
    est.train(_data, epochs=1)
    assert int(est.state.step) == 4  # 64 / (2*8) = 4 steps
    metrics = est.evaluate(lambda cfg: _data(cfg, length=32))
    assert np.isfinite(metrics["loss"]) and "top1" in metrics


def test_estimator_by_name():
    est = Estimator("resnet18", CFG.replace(compute_dtype="bfloat16"))
    assert est.model.depth == 18


def test_keras_frontend_with_reference_callback_set(mesh8, tmp_path):
    model = Model(_model(), CFG)
    model.compile(optimizer="sgd")
    callbacks = [
        BroadcastGlobalVariablesCallback(0),
        MetricAverageCallback(),
        LearningRateWarmupCallback(warmup_epochs=2, verbose=True),
        LearningRateScheduleCallback(multiplier=0.1, start_epoch=30),
        LearningRateScheduleCallback(multiplier=0.01, start_epoch=60),
        LoggerCallback(),
        ModelCheckpointCallback(str(tmp_path / "ckpt")),
    ]
    result = model.fit(
        _data(CFG), epochs=1, callbacks=callbacks, validation_data=_data(CFG, 32)
    )
    assert int(result.state.step) == 4
    assert len(result.history) == 1
    assert "val_top1" in result.history[0]
    # schedule callbacks were consumed into the config
    assert model.config.warmup_epochs == 2
    assert model.config.lr_decay_epochs == (30, 60)
    # checkpoint was written and is restorable
    m2 = Model(_model(), CFG).compile()
    m2.load_weights(str(tmp_path / "ckpt"))
    import jax

    for a, b in zip(
        jax.tree.leaves(model.state.params), jax.tree.leaves(m2.state.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_keras_compile_required():
    model = Model(_model(), CFG)
    with pytest.raises(RuntimeError, match="compile"):
        model.fit(_data(CFG))


def test_keras_bad_optimizer():
    with pytest.raises(ValueError, match="optimizer"):
        Model(_model(), CFG).compile(optimizer="adamw9000")


def test_explicit_frontend(mesh8):
    pieces, state = explicit.setup(
        _model(), CFG, steps_per_epoch=_data(CFG).steps_per_epoch
    )
    data = _data(CFG)
    state = explicit.train_epoch(pieces, state, data, epoch=0)
    assert int(state.step) == 4
    metrics = explicit.validate(pieces, state, _data(CFG, 32))
    assert np.isfinite(metrics["loss"])
    assert 0 <= metrics["top1"] <= 1


def test_frontends_agree(mesh8):
    """Same seed/config/data -> estimator and explicit produce identical
    params (one engine underneath)."""
    import jax

    est = Estimator(lambda cfg: _model(), CFG)
    est.train(_data, epochs=1)

    pieces, state = explicit.setup(
        _model(), CFG, steps_per_epoch=_data(CFG).steps_per_epoch
    )
    state = explicit.train_epoch(pieces, state, _data(CFG), epoch=0)

    for a, b in zip(
        jax.tree.leaves(est.state.params), jax.tree.leaves(state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_runconfig_mesh_is_field():
    rc = RunConfig(model_dir="x", mesh="placeholder")
    assert rc.mesh == "placeholder"


def test_keras_initial_epoch_skips_completed_epochs(mesh8):
    """Reference resume contract (:323-341): initial_epoch=2 with
    epochs=3 runs exactly one epoch of steps."""
    m = Model(_model(), CFG.replace(validation=False))
    m.compile()
    result = m.fit(_data(CFG), epochs=3, initial_epoch=2)
    assert int(m.state.step) == 4  # one epoch: 64/(2*8)
    assert len(result.history) == 1


def test_compute_dtype_reaches_model():
    m32 = Model("resnet18", CFG.replace(compute_dtype="float32"))
    assert m32.module.dtype == jnp.float32
    m16 = Model("resnet18", CFG.replace(compute_dtype="bfloat16"))
    assert m16.module.dtype == jnp.bfloat16


def test_keras_categorical_crossentropy_one_hot(mesh8):
    """Reference Keras mode: categorical CE over the one-hot
    FakeDataGenerator (imagenet_keras_horovod.py:307, data_generator.py
    :48-53)."""
    cfg = CFG.replace(validation=False)
    data = SyntheticImageDataset(
        length=32,
        global_batch_size=cfg.global_batch_size,
        image_size=cfg.image_size,
        num_classes=cfg.num_classes,
        num_physical_batches=2,
        one_hot=True,
    )
    m = Model(_model(), cfg)
    m.compile(loss="categorical_crossentropy")
    result = m.fit(data, epochs=1)
    assert np.isfinite(result.history[-1]["loss"])
    assert 0.0 <= result.history[-1]["accuracy"] <= 1.0


def test_one_hot_evaluation(mesh8):
    """categorical mode evaluates too: eval_metrics_fn reduces one-hot
    labels to hard labels for top-k and uses them for the CE term."""
    cfg = CFG.replace(validation=False)
    train = _data(cfg, length=32)
    # non-divisible length: exercises pad+mask with one-hot labels
    val = _data(cfg, length=24, one_hot=True, exact=True)
    m = Model(_model(), cfg)
    m.compile(loss="categorical_crossentropy")
    m.fit(train, epochs=1)
    metrics = m.evaluate(val)
    assert metrics["samples"] == 24.0
    for k in ("loss", "top1", "top5"):
        assert np.isfinite(metrics[k])
    assert metrics["top5"] >= metrics["top1"]


def test_keras_front_end_trains_bn_model_under_pjit(mesh8):
    """Round 4: ENGINE=pjit now trains BatchNorm models (batch-split
    per-replica BN, models/norm.py) — the Keras compile/fit/evaluate
    path must reach it end to end, not just the raw engine API."""
    cfg = CFG.replace(engine="pjit")
    model = Model(_model(), cfg)
    model.compile(optimizer="momentum")
    result = model.fit(_data(cfg), epochs=1)
    assert int(jax.device_get(result.state.step)) == cfg.fake_data_length // (
        cfg.global_batch_size
    )
    assert result.state.batch_stats  # BN statistics actually tracked
    # exact coverage: 24 = 1.5 batches, trailing half padded + masked
    metrics = model.evaluate(_data(cfg, length=24, exact=True))
    assert np.isfinite(metrics["loss"]) and metrics["samples"] == 24.0
