import threading
import time

import numpy as np

from distributeddeeplearning_tpu.data.pipeline import prefetch_to_device, shard_batch


def _batches(n, size=8):
    for i in range(n):
        yield (np.full((size, 2), i, np.float32), np.full((size,), i, np.int32))


def test_prefetch_yields_all_sharded(mesh8):
    out = list(prefetch_to_device(_batches(5), mesh8, size=2))
    assert len(out) == 5
    imgs, labels = out[3]
    assert imgs.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(imgs), 3.0)


def test_prefetch_zero_size_passthrough(mesh8):
    out = list(prefetch_to_device(_batches(3), mesh8, size=0))
    assert len(out) == 3


def test_prefetch_propagates_producer_error(mesh8):
    def bad():
        yield from _batches(2)
        raise RuntimeError("boom")

    import pytest

    with pytest.raises(RuntimeError, match="boom"):
        list(prefetch_to_device(bad(), mesh8, size=1))


def test_prefetch_early_abandonment_stops_producer(mesh8):
    # Regression: abandoning the generator must terminate the producer
    # thread rather than leaving it blocked on a full queue forever.
    before = threading.active_count()
    it = prefetch_to_device(_batches(100), mesh8, size=2)
    next(it)
    it.close()  # consumer walks away mid-epoch
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_shard_batch_places_on_mesh(mesh8):
    imgs = np.zeros((16, 3), np.float32)
    arr = shard_batch((imgs, np.zeros(16, np.int32)), mesh8)
    assert arr[0].sharding.mesh.shape["data"] == 8
