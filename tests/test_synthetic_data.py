import numpy as np

from distributeddeeplearning_tpu.data.synthetic import SyntheticImageDataset


def _ds(**kw):
    defaults = dict(
        length=1024,
        global_batch_size=64,
        image_size=8,
        num_classes=10,
        num_physical_batches=4,
        seed=42,
    )
    defaults.update(kw)
    return SyntheticImageDataset(**defaults)


def test_virtual_length_and_steps():
    ds = _ds()
    assert len(ds) == 1024
    assert ds.steps_per_epoch == 16
    batches = list(ds.epoch(0))
    assert len(batches) == 16
    imgs, labels = batches[0]
    assert imgs.shape == (64, 8, 8, 3)
    assert labels.shape == (64,)
    assert labels.dtype == np.int32


def test_determinism_same_seed():
    a = next(iter(_ds()))
    b = next(iter(_ds()))
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])


def test_different_seed_differs():
    a = next(iter(_ds(seed=1)))
    b = next(iter(_ds(seed=2)))
    assert not np.array_equal(a[0], b[0])


def test_epochs_differ():
    ds = _ds()
    a = next(ds.epoch(0))
    b = next(ds.epoch(1))
    assert not np.array_equal(a[0], b[0])


def test_process_sharding_disjoint_and_correct_size():
    # DistributedSampler parity: two processes draw different local batches
    # that each cover half the global batch.
    p0 = _ds(process_index=0, process_count=2)
    p1 = _ds(process_index=1, process_count=2)
    a = next(iter(p0))
    b = next(iter(p1))
    assert a[0].shape[0] == 32 and b[0].shape[0] == 32
    assert not np.array_equal(a[0], b[0])
    # both still produce full epochs of global coverage
    assert p0.steps_per_epoch == p1.steps_per_epoch == 16


def test_one_hot():
    ds = _ds(one_hot=True)
    _, labels = next(iter(ds))
    assert labels.shape == (64, 10)
    np.testing.assert_allclose(labels.sum(axis=-1), 1.0)


def test_small_pool_virtualised():
    # pool is tiny but epoch covers the virtual length (reference trick:
    # translation_index, data_generator.py:45-52)
    ds = _ds(num_physical_batches=1)
    assert len(list(ds.epoch(0))) == 16
