"""Gradient checkpointing (remat) tests: numerically transparent,
reachable from config, works through the engines and the PP stages."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models import get_model
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.training import create_train_state, make_train_step
from distributeddeeplearning_tpu.training.train_step import (
    cross_entropy_loss,
    replicate_state,
)

VOCAB, T = 32, 8


def test_remat_env_and_registry_wiring():
    cfg = TrainConfig.from_env({"REMAT": "1", "MODEL": "lm_tiny"})
    assert cfg.remat
    m = get_model(cfg.model, **cfg.model_kwargs())
    assert m.remat
    # conv models ignore the knob instead of erroring
    m2 = get_model("resnet18", **cfg.model_kwargs())
    assert m2.__class__.__name__ == "ResNet"
    v = get_model("vit_ti16", **cfg.model_kwargs())
    assert v.remat


def test_remat_gradients_identical():
    """Remat recomputes the same ops — loss and grads must match the
    stored-activation path to float precision."""
    rng = np.random.RandomState(0)
    rows = rng.randint(0, VOCAB, size=(4, T + 1)).astype(np.int32)
    tokens, labels = jnp.asarray(rows[:, :-1]), jnp.asarray(rows[:, 1:])

    results = {}
    for remat in (False, True):
        model = TransformerLM(
            variant="tiny", vocab_size=VOCAB, max_seq_len=T,
            dtype=jnp.float32, remat=remat,
        )
        import flax.linen as nn

        params = nn.unbox(
            model.init(jax.random.PRNGKey(0), tokens, train=False)["params"]
        )

        def loss_fn(p):
            logits = model.apply({"params": p}, tokens, train=False)
            return cross_entropy_loss(logits, labels)

        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
        results[remat] = (float(loss), jax.device_get(grads))

    assert np.isclose(results[False][0], results[True][0], rtol=1e-7)
    for a, b in zip(
        jax.tree.leaves(results[False][1]), jax.tree.leaves(results[True][1])
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_remat_trains_through_dp_engine(mesh8):
    cfg = TrainConfig(num_classes=VOCAB, batch_size_per_device=2,
                      weight_decay=0.0, compute_dtype="float32", remat=True)
    model = get_model("lm_tiny", **cfg.model_kwargs(), max_seq_len=T)
    assert model.remat
    tx = optax.sgd(0.1)
    state = replicate_state(
        create_train_state(model, cfg, tx, input_shape=(1, T),
                           input_dtype=jnp.int32),
        mesh8,
    )
    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    rng = np.random.RandomState(1)
    rows = rng.randint(0, VOCAB, size=(16, T + 1)).astype(np.int32)
    batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh8)
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_remat_pp_stage_matches_reference(devices):
    """PP with remat'd stages still equals the sequential oracle."""
    from distributeddeeplearning_tpu.models.pipeline_lm import PipelineLM
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.pp_step import (
        create_pp_state,
        make_pp_train_step,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh(axes=("data", "pipe"), shape=(2, 4))
    pl = PipelineLM(variant="tiny", vocab_size=VOCAB, max_seq_len=T,
                    num_stages=4, n_layers=4, dtype=jnp.float32, remat=True)
    cfg = TrainConfig(num_classes=VOCAB, batch_size_per_device=1,
                      weight_decay=0.0, compute_dtype="float32")
    tx = optax.sgd(0.1)
    state = create_pp_state(pl, cfg, tx, mesh, T)
    host_params = jax.device_get(state.params)
    rng = np.random.RandomState(2)
    rows = rng.randint(0, VOCAB, size=(8, T + 1)).astype(np.int32)
    spec = NamedSharding(mesh, P("data"))
    step = make_pp_train_step(pl, tx, mesh, cfg, num_microbatches=2,
                              donate_state=False)
    _, metrics = step(
        state,
        (jax.device_put(rows[:, :-1], spec), jax.device_put(rows[:, 1:], spec)),
    )

    def ref_loss(params):
        logits = pl.apply_reference(params, jnp.asarray(rows[:, :-1]), train=True)
        return cross_entropy_loss(logits, jnp.asarray(rows[:, 1:]))

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_loss(host_params)), rtol=1e-5
    )
