"""Test backbone: 8 virtual CPU devices running the real distributed code.

This is the TPU-build analogue of the reference's local smoke test
(``mpirun -np 2 -H localhost:2`` in ``Horovod*/00_CreateImageAndTest.ipynb``
cells 6-10, SURVEY.md §4.2): the *same* mesh/shard_map code path that runs
on a pod runs here on 8 forced host devices. Must run before jax
initialises a backend; the axon TPU plugin force-sets
``jax_platforms='axon,cpu'`` at interpreter start, so we re-force cpu via
config (env vars alone are overridden).
"""

import os
import sys

# tests import repo-root helpers (scripts/…) — pytest only inserts
# tests/' own dir, so bare `pytest` from elsewhere needs the root added.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Data-driven fast/full split (round 5): tests/heavy_tests.txt lists the
# nodeids measured ≥ ~10 s on the 1-vCPU reference host (regenerate from
# a full `pytest --durations=0` run). `make test-fast` deselects them
# with `-m "not heavy"`; the full suite runs everything.
_HEAVY_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "heavy_tests.txt")


def pytest_collection_modifyitems(config, items):
    try:
        with open(_HEAVY_FILE) as f:
            heavy = {ln.strip() for ln in f if ln.strip()}
    except OSError:
        return
    for item in items:
        if item.nodeid in heavy:
            item.add_marker(pytest.mark.heavy)
            # `slow` rides along: time-bounded runs (the driver's tier-1
            # battery uses -m 'not slow') deselect the measured-heavy
            # oracle tier; `make test` still runs everything.
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 forced CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh

    return data_parallel_mesh()
