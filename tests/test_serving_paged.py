"""Paged KV-cache pool + prefix-caching oracles (serving/blocks.py,
the paged SlotEngine layout, scheduler block gating).

Three claims, all pinned here:

1. **Allocator invariants** — alloc/free/refcount/copy-on-write ledger
   arithmetic, trash-block reservation, LRU retention + eviction of
   zero-ref prefix-cached blocks, all-or-nothing exhaustion.
2. **Parity** — a request decoded through the paged pool emits *bitwise*
   the tokens sequential ``inference.generate`` emits, under the same
   adversarial co-scheduling the dense oracles stage (staggered joins,
   mixed buckets, mid-stream cancellation, mixed greedy/sampled) — and
   the program set stays closed at ``len(buckets) + 1`` with zero
   backend compiles across the churn.
3. **Prefix sharing** — a request whose prompt shares a block-aligned
   prefix with a cached one maps its leading table entries to the SAME
   physical blocks, prefills only the divergent suffix (the shared
   blocks are written exactly once — their bytes are bitwise unchanged
   by the second prefill), and still emits bitwise-identical tokens to
   an unshared run. Block exhaustion holds requests at the queue head
   (FIFO) and surfaces as ``QueueFull`` backpressure at submit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.inference import generate
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.serving import (
    BlockAllocator,
    BlockPoolExhausted,
    QueueFull,
    ReqSpec,
    Request,
    ServeConfig,
    Server,
    SlotEngine,
)
from distributeddeeplearning_tpu.serving.blocks import (
    TRASH_BLOCK,
    hash_prefix_chain,
)

VOCAB, MAX_LEN = 64, 32
BUCKETS = (4, 8, 16)
BLOCK = 4


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    import flax.linen as nn

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


@pytest.fixture(scope="module")
def _engine(model, params):
    eng = SlotEngine(
        model, params, num_slots=4, max_len=MAX_LEN, buckets=BUCKETS,
        kv_layout="paged", block_size=BLOCK,
    )
    eng.warmup()
    return eng


@pytest.fixture
def engine(_engine):
    """The shared warmed paged engine, guaranteed empty per test."""
    for s in _engine.active_slots:
        _engine.release(s)
    yield _engine
    for s in _engine.active_slots:
        _engine.release(s)


def _prompt(rng, n):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _assert_request_parity(h, model, params):
    r = h.request
    rng = (
        jax.random.PRNGKey(r.rng) if isinstance(r.rng, (int, np.integer))
        else (None if r.rng is None else jnp.asarray(r.rng, jnp.uint32))
    )
    ref = np.asarray(generate(
        model, params, np.asarray(r.prompt, np.int32)[None],
        max_new_tokens=r.max_new_tokens, temperature=r.temperature,
        top_k=r.top_k, top_p=r.top_p, eos_token=r.eos_token, rng=rng,
    ))[0]
    got = h.tokens
    assert got.shape[0] <= ref.shape[0], (got.shape, ref.shape)
    np.testing.assert_array_equal(got, ref[: got.shape[0]])


def _paged_k_blocks(engine, block_ids):
    """Bitwise snapshot of the given physical blocks across every
    layer's K pool."""
    idx = np.asarray(block_ids)
    flat = engine._flatten(engine._unfreeze(engine._pool))
    return {
        "/".join(p): np.asarray(leaf[idx])
        for p, leaf in flat.items()
        if p[-1] in ("paged_k", "paged_v")
    }


# -- allocator ledger ------------------------------------------------------


def test_allocator_basic_and_trash_reserved():
    a = BlockAllocator(num_blocks=6, block_size=4)
    assert a.capacity == 5 and a.free_count == 5
    got = a.alloc(5)
    assert TRASH_BLOCK not in got
    assert sorted(got) == [1, 2, 3, 4, 5]
    assert a.free_count == 0 and a.live_count == 5
    with pytest.raises(BlockPoolExhausted):
        a.alloc(1)
    for b in got:
        a.decref(b)
    assert a.free_count == 5 and a.live_count == 0
    assert a.blocks_for_tokens(0) == 0
    assert a.blocks_for_tokens(1) == 1
    assert a.blocks_for_tokens(4) == 1
    assert a.blocks_for_tokens(5) == 2


def test_allocator_refcount_and_prefix_match():
    a = BlockAllocator(num_blocks=8, block_size=2)
    toks = np.arange(6, dtype=np.int32)
    bids = a.alloc(3)
    assert a.register_prefix(toks, bids) == 3
    # a second registration of the same content is a no-op
    other = a.alloc(3)
    assert a.register_prefix(toks, other) == 0
    # matching refs the SAME physical blocks
    m = a.match_prefix(toks, max_tokens=6)
    assert m == bids
    assert all(a.refcount(b) == 2 for b in bids)
    # the max_tokens cap stops the chain early (serving caps at t-1)
    a.release_match(m)
    m = a.match_prefix(toks, max_tokens=5)
    assert m == bids[:2]
    a.release_match(m)
    # divergent content after block 0 only matches the agreeing prefix
    toks2 = np.array([0, 1, 9, 9, 4, 5], np.int32)
    m = a.match_prefix(toks2, max_tokens=6)
    assert m == bids[:1]
    a.release_match(m)


def test_allocator_lru_retention_and_eviction():
    a = BlockAllocator(num_blocks=4, block_size=2)  # 3 usable
    toks = np.arange(6, dtype=np.int32)
    bids = a.alloc(3)
    a.register_prefix(toks, bids)
    for b in bids:
        a.decref(b)
    # zero-ref but registered: retained, still matchable AND allocatable
    assert a.free_count == 3 and a.live_count == 0
    m = a.match_prefix(toks, max_tokens=6)
    assert m == bids
    for b in m:
        a.decref(b)
    # allocation pressure evicts LRU-first and drops the hash mapping
    fresh = a.alloc(2)
    assert set(fresh) == set(bids[:2])
    assert a.stats["evicted"] == 2
    assert a.match_prefix(toks, max_tokens=6) == []  # chain broken at 0
    with pytest.raises(BlockPoolExhausted):
        a.alloc(2)  # only the last cached block remains


def test_allocator_copy_on_write():
    a = BlockAllocator(num_blocks=6, block_size=2)
    toks = np.arange(4, dtype=np.int32)
    bids = a.alloc(2)
    a.register_prefix(toks, bids)
    # shared block: writer gets a FRESH block, sharer keeps the original
    a.incref(bids[0])
    private = a.ensure_private(bids[0])
    assert private != bids[0]
    assert a.refcount(bids[0]) == 1 and a.refcount(private) == 1
    assert a.stats["cow"] == 1
    # exclusive-but-registered block: unregistered in place (its cached
    # content is about to change), same id back
    assert a.ensure_private(bids[1]) == bids[1]
    assert a.match_prefix(toks, max_tokens=4) == [bids[0]]
    a.release_match([bids[0]])
    # exclusive unregistered block: identity
    assert a.ensure_private(private) == private


def test_hash_chain_is_position_dependent():
    bs = 4
    t1 = np.arange(8, dtype=np.int32)
    t2 = np.concatenate([np.arange(4, 8), np.arange(4)]).astype(np.int32)
    h1, h2 = hash_prefix_chain(t1, bs), hash_prefix_chain(t2, bs)
    assert len(h1) == 2 and len(h2) == 2
    assert h1[0] != h2[0]          # content differs
    assert h1[1] != h2[1]          # same bytes, different prefix -> differs
    assert hash_prefix_chain(t1[:7], bs) == h1[:1]  # partial tail excluded


# -- paged engine parity ---------------------------------------------------


def test_paged_parity_greedy_staggered_mixed_lengths(engine, model, params):
    """The dense tier's flagship oracle on the paged pool: 8 greedy
    requests over 4 slots, mixed buckets, staggered joins — bitwise."""
    rng = np.random.RandomState(0)
    server = Server(engine, prefills_per_step=1)
    handles = [
        server.submit(Request(prompt=_prompt(rng, n), max_new_tokens=m))
        for n, m in [(3, 6), (7, 9), (12, 4), (16, 10),
                     (4, 12), (9, 3), (14, 7), (5, 5)]
    ]
    server.drain()
    assert all(h.status == "done" for h in handles)
    for h in handles:
        _assert_request_parity(h, model, params)
    # every block returned (some parked in the prefix cache, all free)
    assert engine.allocator.live_count == 0
    assert engine.allocator.free_count == engine.allocator.capacity


def test_paged_sampled_churn_zero_recompiles(engine, model, params):
    """Seeded sampling + cancellation churn on the paged pool: zero
    backend compiles, closed program set, every stream bitwise."""
    from jax._src import monitoring

    compiles = []
    monitoring.register_event_duration_secs_listener(
        lambda event, duration, **kw: compiles.append(event)
        if "backend_compile" in event else None
    )
    baseline = len(compiles)

    rng = np.random.RandomState(1)
    server = Server(engine, prefills_per_step=2)
    mk = lambda n, m, seed, **kw: server.submit(Request(  # noqa: E731
        prompt=_prompt(rng, n), max_new_tokens=m, rng=seed, **kw
    ))
    wave1 = [
        mk(3, 10, 11, temperature=0.9, top_k=8),
        mk(8, 12, 12, temperature=0.7, top_k=5),
        mk(13, 12, 13),
        mk(16, 8, 14, temperature=1.1, top_k=40, top_p=0.9),
    ]
    for _ in range(4):
        server.step()
    victim = wave1[1]
    victim.cancel()
    wave2 = [
        mk(5, 9, 21, temperature=0.8, top_k=6),
        mk(10, 6, 22, temperature=1.0, top_p=0.8),
    ]
    server.drain()
    assert len(compiles) == baseline, compiles[baseline:]
    assert engine.compile_count == len(BUCKETS) + 1
    assert victim.status == "cancelled"
    assert 0 < len(victim.new_tokens) < victim.request.max_new_tokens
    for h in wave1 + wave2:
        _assert_request_parity(h, model, params)


def test_paged_generate_engine_routing_bitwise(engine, model, params):
    """The drop-in generate(engine=...) route over the paged pool."""
    rng = np.random.RandomState(4)
    server = Server(engine)
    p1 = rng.randint(0, VOCAB, size=(1, 6)).astype(np.int32)
    for kw in (
        dict(),
        dict(temperature=0.8, top_k=7, rng=jax.random.PRNGKey(3)),
    ):
        ref = np.asarray(generate(model, params, p1, max_new_tokens=8, **kw))
        got = np.asarray(generate(model, params, p1, max_new_tokens=8,
                                  engine=server, **kw))
        np.testing.assert_array_equal(got, ref)


# -- prefix-sharing oracle -------------------------------------------------


def test_prefix_sharing_oracle(engine, model, params):
    """Two requests sharing a 12-token prompt: the second maps its two
    leading table entries to the FIRST request's physical blocks,
    prefills only the 4-token suffix (bucket 4, not 16), leaves the
    shared blocks bitwise untouched — and both emit exactly what
    unshared sequential generate emits."""
    rng = np.random.RandomState(7)
    prompt = _prompt(rng, 12)
    server = Server(engine)

    hA = server.submit(Request(
        prompt=prompt, max_new_tokens=8, temperature=0.8, top_k=5, rng=11,
    ))
    server.drain()
    a_info = dict(engine.last_prefill)
    assert a_info["shared_blocks"] == 0 and a_info["start"] == 0
    assert a_info["bucket"] == 16
    # full blocks = 12 // 4 = 3, but sharing is capped at t-1 = 11
    # tokens -> 2 shareable blocks
    shared_ids = a_info["blocks"][:2]
    before = _paged_k_blocks(engine, shared_ids)

    hB = server.submit(Request(
        prompt=prompt, max_new_tokens=8, temperature=0.8, top_k=5, rng=99,
    ))
    server.drain()
    b_info = dict(engine.last_prefill)
    assert b_info["shared_blocks"] == 2
    assert b_info["start"] == 2 * BLOCK
    assert b_info["bucket"] == 4                    # suffix-only prefill
    assert b_info["blocks"][:2] == shared_ids       # same physical blocks

    # prefilled exactly once: the second prefill did not rewrite them
    after = _paged_k_blocks(engine, shared_ids)
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])

    _assert_request_parity(hA, model, params)
    _assert_request_parity(hB, model, params)
    assert engine.allocator.stats["prefix_hit_requests"] >= 1


def test_prefix_sharing_concurrent_co_resident(engine, model, params):
    """Prefix reuse while the donor is STILL RUNNING: refcounts keep the
    shared blocks alive and both streams stay bitwise."""
    rng = np.random.RandomState(8)
    prompt = _prompt(rng, 8)
    server = Server(engine, prefills_per_step=1)
    hA = server.submit(Request(prompt=prompt, max_new_tokens=10, rng=1))
    hB = server.submit(Request(prompt=prompt, max_new_tokens=10, rng=2))
    server.step()   # admits A (full prefill, registers blocks)
    server.step()   # admits B -> shares A's live blocks
    assert engine.last_prefill["shared_blocks"] == 1  # cap 7 tokens -> 1
    shared = engine.last_prefill["blocks"][0]
    assert engine.allocator.refcount(shared) == 2
    server.drain()
    _assert_request_parity(hA, model, params)
    _assert_request_parity(hB, model, params)
    assert engine.allocator.live_count == 0


def test_prefix_cache_off_never_shares(model, params):
    eng = SlotEngine(
        model, params, num_slots=2, max_len=MAX_LEN, buckets=(8,),
        kv_layout="paged", block_size=BLOCK, prefix_cache=False,
    )
    eng.warmup()
    prompt = np.arange(8, dtype=np.int32) % VOCAB
    server = Server(eng)
    server.submit(Request(prompt=prompt, max_new_tokens=4))
    server.drain()
    server.submit(Request(prompt=prompt, max_new_tokens=4))
    server.drain()
    assert eng.last_prefill["shared_blocks"] == 0
    assert eng.allocator.stats["prefix_hit_blocks"] == 0


# -- backpressure / admission gating ---------------------------------------


def test_block_exhaustion_backpressure(model, params):
    """A pool sized for ~2 co-resident requests holds the third at the
    queue head (no admission, no error), a full queue raises QueueFull
    at submit, and everything still completes bitwise once blocks free
    up."""
    eng = SlotEngine(
        model, params, num_slots=4, max_len=MAX_LEN, buckets=(8,),
        kv_layout="paged", block_size=BLOCK, num_blocks=9,  # 8 usable
        prefix_cache=False,
    )
    eng.warmup()
    server = Server(eng, queue_depth=2)
    rng = np.random.RandomState(3)
    # each request needs ceil((8 + 8 - 1)/4) = 4 blocks -> 2 fit
    mk = lambda: Request(  # noqa: E731
        prompt=_prompt(rng, 8), max_new_tokens=8
    )
    running = [server.submit(mk()), server.submit(mk())]
    server.step()
    server.step()
    assert len(server._by_slot) == 2            # both admitted
    assert eng.allocator.free_count == 0
    queued = [server.submit(mk()), server.submit(mk())]
    server.step()
    assert queued[0].status == "queued"         # blocked on blocks,
    assert len(server._by_slot) == 2            # not on slots
    with pytest.raises(QueueFull):
        server.submit(mk())                     # backpressure surfaces
    assert server.stats["rejected"] == 1
    server.drain()
    for h in running + queued:
        assert h.status == "done"
        _assert_request_parity(h, model, params)
    assert eng.allocator.live_count == 0


def test_paged_validation_rejects_oversized_request(model, params):
    eng = SlotEngine(
        model, params, num_slots=2, max_len=MAX_LEN, buckets=BUCKETS,
        kv_layout="paged", block_size=BLOCK, num_blocks=4,  # 3 usable
    )
    with pytest.raises(ValueError, match="KV blocks"):
        # needs ceil((16+10-1)/4) = 7 blocks > 3
        eng.validate_spec(ReqSpec(np.zeros(16, np.int32), 10))
    # a fitting request validates
    eng.validate_spec(ReqSpec(np.zeros(8, np.int32), 4))


def test_paged_serve_config_from_env():
    cfg = ServeConfig.from_env({
        "SERVE_KV_LAYOUT": "paged", "SERVE_BLOCK_SIZE": "8",
        "SERVE_NUM_BLOCKS": "33", "SERVE_PREFIX_CACHE": "0",
        "SERVE_SLOTS": "4",
    })
    assert cfg.kv_layout == "paged"
    assert cfg.block_size == 8 and cfg.num_blocks == 33
    assert cfg.prefix_cache is False
    kw = cfg.engine_kwargs()
    assert kw["kv_layout"] == "paged" and kw["num_blocks"] == 33
    dflt = ServeConfig.from_env({})
    assert dflt.kv_layout == "dense" and dflt.prefix_cache is True
    assert "block_size" not in dflt.engine_kwargs()


def test_paged_server_build_from_config(model, params):
    server = Server.build(model, params, ServeConfig(
        num_slots=2, buckets=(8,), kv_layout="paged", block_size=8,
    ))
    assert server.engine.kv_layout == "paged"
    assert server.engine.block_size == 8
    assert server.engine.allocator is not None
    # dense-equivalent default pool: slots * ceil(max_len/bs) + trash
    assert server.engine.num_blocks == 2 * (MAX_LEN // 8) + 1


# -- obs plumbing ----------------------------------------------------------


def test_paged_obs_gauges_and_report(engine, tmp_path):
    """Block-pool gauges land on the bus and the report's serving view
    renders the pool-utilization line."""
    from distributeddeeplearning_tpu import obs
    from distributeddeeplearning_tpu.obs.report import (
        load, render, summarize,
    )

    bus = obs.configure(str(tmp_path), run_id="serve-paged-test", proc=0,
                        install_handlers=False)
    try:
        server = Server(engine)
        rng = np.random.RandomState(9)
        prompt = _prompt(rng, 8)
        hs = [
            server.submit(Request(prompt=prompt, max_new_tokens=4))
            for _ in range(2)
        ]
        server.drain()
        assert all(h.status == "done" for h in hs)
        bus.flush()
    finally:
        obs.reset()
    summary = summarize(load([str(tmp_path)]))
    srv = summary["serving"]
    assert srv is not None
    assert srv["block_pool_total"] == float(engine.allocator.capacity)
    assert srv["block_pool_free"] is not None
    assert srv["prefix_hits"] and srv["prefix_hits"] > 0
    text = render(summary)
    assert "block pool" in text
    assert "prefix hits" in text
