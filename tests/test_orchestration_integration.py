"""Orchestration BEYOND dry-run: a PATH-shimmed fake ``gcloud`` records
every argv and plays scripted outcomes, so the full provision → setup →
submit → status → stream → stop → teardown loop actually EXECUTES its
subprocess layer (VERDICT r2 Missing #1 / Next #5 — the reference's
notebook really ran cells 19-26; dry-run argv assertions alone cannot
catch a swallowed rc).

Error handling exercised: nonzero rc surfacing with the failing command
named, pod-already-exists idempotency, ssh retry-with-backoff, and
abort-on-first-failure for partial-worker setup.
"""

import json
import os
import stat
import sys
import textwrap

import pytest

from distributeddeeplearning_tpu.orchestration import provision, submit

FAKE_GCLOUD = textwrap.dedent(
    """\
    #!{python}
    import json, os, sys

    with open(os.environ["FAKE_GCLOUD_LOG"], "a") as f:
        f.write(json.dumps(sys.argv[1:]) + "\\n")
    rules = json.loads(os.environ.get("FAKE_GCLOUD_RULES", "[]"))
    argv = " ".join(sys.argv[1:])
    for rule in rules:
        if rule["match"] in argv:
            if "fail_times" in rule:  # transient: fail N times, then ok
                cf = rule["counter"]
                n = int(open(cf).read()) if os.path.exists(cf) else 0
                open(cf, "w").write(str(n + 1))
                if n < rule["fail_times"]:
                    sys.stderr.write(rule.get("stderr", "transient\\n"))
                    sys.exit(rule.get("rc", 255))
                break
            if "stdout_seq" in rule:  # scripted per-call outputs
                cf = rule["counter"]
                n = int(open(cf).read()) if os.path.exists(cf) else 0
                open(cf, "w").write(str(n + 1))
                seq = rule["stdout_seq"]
                sys.stdout.write(seq[min(n, len(seq) - 1)])
                sys.exit(rule.get("rc", 0))
            sys.stdout.write(rule.get("stdout", ""))
            sys.stderr.write(rule.get("stderr", ""))
            sys.exit(rule.get("rc", 0))
    sys.stdout.write("ok\\n")
    sys.exit(0)
    """
)


@pytest.fixture
def fake_gcloud(tmp_path, monkeypatch):
    """Install a fake gcloud on PATH; returns helpers to read the argv
    log and to script outcomes."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    exe = bindir / "gcloud"
    exe.write_text(FAKE_GCLOUD.format(python=sys.executable))
    exe.chmod(exe.stat().st_mode | stat.S_IEXEC)
    log = tmp_path / "gcloud_argv.jsonl"
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_GCLOUD_LOG", str(log))
    monkeypatch.delenv("FAKE_GCLOUD_RULES", raising=False)

    class Shim:
        def calls(self):
            if not log.exists():
                return []
            return [json.loads(l) for l in log.read_text().splitlines()]

        def set_rules(self, rules):
            monkeypatch.setenv("FAKE_GCLOUD_RULES", json.dumps(rules))

        def clear(self):
            if log.exists():
                log.unlink()

    return Shim()


def _flags(tmp_path, *extra):
    return [
        "--env-file", str(tmp_path / ".env"),
        "--tpu", "ddl-pod", "--zone", "us-west4-a",
        "--retry-delay", "0.01",
        *extra,
    ]


def test_full_lifecycle_executes_against_fake_gcloud(
    fake_gcloud, tmp_path, capsys
):
    """provision storage → pod-create → setup → submit run --detach →
    status → stream → stop → pod-delete, every subprocess really spawned
    and rc-checked, .env threaded between the CLIs like the reference's
    dotenv workflow."""
    envf = str(tmp_path / ".env")
    assert provision.main(
        _flags(tmp_path, "storage", "--bucket", "gs://ddl-bucket",
               "--data", str(tmp_path))
    ) == 0
    assert provision.main(_flags(tmp_path, "pod-create")) == 0
    assert provision.main(_flags(tmp_path, "setup", "--bucket", "ddl-bucket")) == 0
    manifest = tmp_path / "job.json"
    assert submit.main([
        "--env-file", envf,  # tpu/zone come from .env written above
        "run", "--job", "j1", "--detach", "--env", "FAKE=True",
        "--manifest", str(manifest), "examples/imagenet_keras_tpu.py",
    ]) == 0
    for action in (["status", "--job", "j1"],
                   ["stream", "--job", "j1", "--no-follow"],
                   ["stop", "--job", "j1"]):
        assert submit.main(["--env-file", envf, *action]) == 0
    assert provision.main(_flags(tmp_path, "pod-delete")) == 0

    calls = fake_gcloud.calls()
    joined = [" ".join(c) for c in calls]
    # the lifecycle really hit the fake binary, in order
    order = [
        "storage buckets create gs://ddl-bucket",
        "storage rsync",
        "compute tpus tpu-vm create ddl-pod",
        "compute tpus tpu-vm ssh ddl-pod",   # setup mkdir
        "compute tpus tpu-vm scp",           # code staging
        "compute tpus tpu-vm ssh",           # submit run
        "compute tpus tpu-vm ssh",           # status
        "compute tpus tpu-vm ssh",           # stream
        "compute tpus tpu-vm ssh",           # stop
        "compute tpus tpu-vm delete ddl-pod",
    ]
    idx = -1
    for needle in order:
        nxt = next(
            (i for i in range(idx + 1, len(joined)) if needle in joined[i]),
            None,
        )
        assert nxt is not None, (needle, joined)
        idx = nxt
    # manifest written (reference cell-15 job JSON)
    m = json.loads(manifest.read_text())
    assert m["job"] == "j1" and m["tpu"] == "ddl-pod" and m["detach"]
    # .env threading (TPU_NAME/ZONE/BUCKET persisted)
    env = (tmp_path / ".env").read_text()
    assert "TPU_NAME=ddl-pod" in env and "BUCKET=gs://ddl-bucket" in env


def test_pod_already_exists_is_idempotent(fake_gcloud, tmp_path, capsys):
    fake_gcloud.set_rules([{
        "match": "tpu-vm create",
        "rc": 1,
        "stderr": "ERROR: (gcloud.compute.tpus.tpu-vm.create) "
                  "ALREADY_EXISTS: Resource already exists\n",
    }])
    assert provision.main(_flags(tmp_path, "pod-create")) == 0
    out = capsys.readouterr().out
    assert "already exists" in out and "continuing" in out


def test_pod_create_quota_error_surfaces(fake_gcloud, tmp_path, capsys):
    fake_gcloud.set_rules([{
        "match": "tpu-vm create",
        "rc": 1,
        "stderr": "ERROR: RESOURCE_EXHAUSTED: quota exceeded\n",
    }])
    assert provision.main(_flags(tmp_path, "pod-create")) == 1
    out = capsys.readouterr().out
    assert "ERROR: step failed (rc=1)" in out and "tpu-vm create" in out


def test_ssh_retry_with_backoff_then_succeeds(fake_gcloud, tmp_path, capsys):
    """The first setup ssh step fails twice (key propagation window),
    then succeeds — setup completes and the log shows 3 attempts."""
    counter = tmp_path / "ssh_fail_count"
    fake_gcloud.set_rules([{
        "match": "tpu-vm ssh",
        "fail_times": 2,
        "counter": str(counter),
        "rc": 255,
        "stderr": "ssh: connect to host: Connection refused\n",
    }])
    assert provision.main(_flags(tmp_path, "setup")) == 0
    out = capsys.readouterr().out
    assert "ssh attempt 1/3 failed (rc=255)" in out
    assert "ssh attempt 2/3 failed (rc=255)" in out
    ssh_calls = [c for c in fake_gcloud.calls() if "ssh" in c]
    assert len(ssh_calls) >= 3  # two failures + the success (+ later steps)


def test_persistent_worker_failure_aborts_setup(fake_gcloud, tmp_path, capsys):
    """A worker that never comes up: setup exhausts retries, names the
    failing command, and does NOT run the remaining steps against a
    half-configured pod."""
    fake_gcloud.set_rules([{
        "match": "tpu-vm scp",
        "rc": 255,
        "stderr": "ERROR: worker 3: connection timed out\n",
    }])
    rc = provision.main(_flags(tmp_path, "setup", "--bucket", "ddl-bucket"))
    assert rc == 255
    out = capsys.readouterr().out
    assert "ERROR: step failed (rc=255)" in out and "scp" in out
    joined = [" ".join(c) for c in fake_gcloud.calls()]
    # scp retried (it's an ssh-family step), but nothing after it ran
    scp_attempts = [c for c in joined if "tpu-vm scp" in c]
    assert len(scp_attempts) == 3
    after = [c for c in joined if "rsync --recursive gs://" in c]
    assert not after  # the data-mount step never executed


def test_foreground_submit_failure_rc_surfaces(fake_gcloud, tmp_path, capsys):
    fake_gcloud.set_rules([{
        "match": "tpu-vm ssh",
        "rc": 7,
        "stderr": "training crashed\n",
    }])
    envf = str(tmp_path / ".env")
    rc = submit.main([
        "--env-file", envf, "--tpu", "ddl-pod", "--zone", "us-west4-a",
        "run", "--job", "j2", "examples/imagenet_keras_tpu.py",
    ])
    assert rc == 7
    err = capsys.readouterr().err
    assert "ERROR: command failed (rc=7)" in err


def test_multislice_wait_polls_until_active(fake_gcloud, tmp_path, capsys):
    """wait_for_multislice really POLLS: the fake scripts a
    PROVISIONING → PROVISIONING → ACTIVE sequence, so the loop must
    iterate three times before returning 0. FAILED aborts with rc 1, and
    persistent describe errors fail fast with the stderr surfaced
    (instead of polling blind for the full timeout)."""
    fake_gcloud.set_rules([
        {
            "match": "queued-resources describe",
            "stdout_seq": ["PROVISIONING\n", "PROVISIONING\n", "ACTIVE\n"],
            "counter": str(tmp_path / "seq_counter"),
        },
    ])
    rc = provision.wait_for_multislice(
        "ms", "z", timeout_s=5.0, poll_s=0.01
    )
    assert rc == 0
    describes = [
        c for c in fake_gcloud.calls() if "queued-resources" in " ".join(c)
    ]
    assert len(describes) == 3, describes
    out = capsys.readouterr().out
    assert out.count("PROVISIONING") == 2 and "ACTIVE" in out

    fake_gcloud.set_rules([
        {"match": "queued-resources describe", "stdout": "FAILED\n"},
    ])
    assert provision.wait_for_multislice("ms", "z", timeout_s=5.0,
                                         poll_s=0.01) == 1
    assert "FAILED" in capsys.readouterr().out

    fake_gcloud.set_rules([
        {"match": "queued-resources describe", "rc": 1,
         "stderr": "ERROR: (gcloud.auth) token expired\n"},
    ])
    assert provision.wait_for_multislice("ms", "z", timeout_s=60.0,
                                         poll_s=0.01) == 1
    out = capsys.readouterr().out
    assert "token expired" in out and "keeps failing" in out


def test_multislice_submit_targets_every_node(fake_gcloud, tmp_path,
                                              monkeypatch, capsys):
    """submit on a multi-slice pod fans run/status/stop over the nodes
    tpu-0…tpu-(N-1) (TPU_NAME is the queued-resource name, which no
    tpu-vm command can address) and requires --detach for run."""
    envf = tmp_path / ".env"
    envf.write_text("TPU_NAME=ms\nZONE=z\nSLICES=2\n")
    flags = ["--env-file", str(envf)]
    rc = submit.main(flags + ["--dry-run"] + [
        "run", "--detach", "--job", "j1",
        "--manifest", str(tmp_path / "m.json"),
        "examples/imagenet_keras_tpu.py",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ssh ms-0" in out and "ssh ms-1" in out
    manifest = json.loads((tmp_path / "m.json").read_text())
    assert manifest["slices"] == 2 and manifest["nodes"] == ["ms-0", "ms-1"]
    # foreground run is refused — all slices must launch concurrently
    with pytest.raises(SystemExit):
        submit.main(flags + ["--dry-run", "run", "--job", "j2", "x.py"])
    capsys.readouterr()
    # status loops every node; stream picks one slice
    assert submit.main(flags + ["--dry-run", "status", "--job", "j1"]) == 0
    out = capsys.readouterr().out
    assert "ssh ms-0" in out and "ssh ms-1" in out
    assert submit.main(
        flags + ["--dry-run", "stream", "--job", "j1", "--slice", "1"]
    ) == 0
    out = capsys.readouterr().out
    assert "ssh ms-1" in out and "ms-0" not in out


def test_multislice_stop_reaches_all_nodes_despite_failure(
    fake_gcloud, tmp_path, capsys
):
    """stop must address EVERY slice node even when one ssh fails —
    returning early would leave a half-stopped job wedged at its next
    collective. First nonzero rc is still reported."""
    envf = tmp_path / ".env"
    envf.write_text("TPU_NAME=ms\nZONE=z\nSLICES=2\n")
    fake_gcloud.set_rules([
        {"match": "ssh ms-0", "rc": 255, "stderr": "conn refused\n"},
    ])
    rc = submit.main([
        "--env-file", str(envf), "--retry-delay", "0.01",
        "stop", "--job", "j1",
    ])
    assert rc == 255
    calls = [" ".join(c) for c in fake_gcloud.calls()]
    # the persistent failure was retried with backoff before giving up
    assert sum("ssh ms-0" in c for c in calls) == 3
    assert any("ssh ms-1" in c for c in calls)  # still reached


def test_multislice_partial_launch_prints_cleanup_guidance(
    fake_gcloud, tmp_path, capsys
):
    """run --detach failing on slice 1 after slice 0 launched must name
    the cleanup command — the nohup'd job on slice 0 is wedged at the
    DCN join."""
    envf = tmp_path / ".env"
    envf.write_text("TPU_NAME=ms\nZONE=z\nSLICES=2\n")
    fake_gcloud.set_rules([
        {"match": "ssh ms-1", "rc": 255, "stderr": "conn refused\n"},
    ])
    rc = submit.main([
        "--env-file", str(envf), "--retry-delay", "0.01",
        "run", "--detach", "--job", "j9", "x.py",
    ])
    assert rc == 255
    err = capsys.readouterr().err
    assert "submit stop --job j9" in err and "ms-1" in err


def test_submit_stream_retries_transient_ssh(fake_gcloud, tmp_path, capsys):
    """The provisioner's ssh retry/backoff policy now covers submit's
    stream/status/stop: a transiently-refused ssh (TPU-VM right after
    creation) is retried instead of failing the action on attempt 1."""
    envf = tmp_path / ".env"
    envf.write_text("TPU_NAME=ddl-pod\nZONE=z\n")
    fake_gcloud.set_rules([{
        "match": "tpu-vm ssh",
        "fail_times": 1,
        "rc": 255,
        "stderr": "conn refused\n",
        "counter": str(tmp_path / "stream_counter"),
    }])
    rc = submit.main([
        "--env-file", str(envf), "--retry-delay", "0.01",
        "stream", "--job", "j1", "--no-follow",
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "gcloud attempt 1/3 failed (rc=255)" in err
    calls = [" ".join(c) for c in fake_gcloud.calls()]
    assert sum("tpu-vm ssh" in c for c in calls) == 2  # fail, then ok


def test_multislice_stream_slice_out_of_range_rejected(tmp_path, capsys):
    envf = tmp_path / ".env"
    envf.write_text("TPU_NAME=ms\nZONE=z\nSLICES=2\n")
    with pytest.raises(SystemExit):
        submit.main(["--env-file", str(envf), "--dry-run",
                     "stream", "--job", "j1", "--slice", "5"])
    assert "out of range" in capsys.readouterr().err


def test_multislice_setup_uses_local_smoke(fake_gcloud, tmp_path, capsys):
    """Per-node sequential setup must NOT run the global
    jax.distributed.initialize() smoke (it would barrier on slices whose
    setup hasn't started); single-slice setup keeps the global check."""
    assert provision.main(
        _flags(tmp_path, "setup", "--slices", "2")
    ) == 0
    out = capsys.readouterr().out
    assert "local_device_count" in out
    assert "distributed.initialize" not in out
    assert provision.main(_flags(tmp_path, "setup")) == 0
    assert "distributed.initialize" in capsys.readouterr().out
