"""Fault-tolerance oracles: step-granular checkpointing, corrupt-latest
fallback, and the resume-equivalence criterion — an interrupted-and-
resumed run must end BITWISE-equal to an uninterrupted one, because
restore is exact (orbax), the data stream is deterministic per
(seed, epoch), and the engines are bitwise run-to-run deterministic
(``tests/test_determinism.py``).

Tiers:

* fast — manager keying/fallback units on plain pytrees, plus an
  in-process mid-epoch resume equivalence (simulated preemption:
  newer checkpoints deleted, fit resumed from a mid-epoch key);
* heavy (``tests/heavy_tests.txt``) — the ISSUE 4 acceptance runs:
  2-OS-process worlds under ``launch.py --max-restarts`` where a
  FAULT_PLAN SIGKILLs rank 1 mid-epoch and the supervisor resumes from
  the step checkpoint, across the dp and pjit engines; and the NaN
  guard halting a supervised world with the non-retryable code.
"""

import json
import os
import re
import shutil
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.training.checkpoint import CheckpointManager

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB, T = 64, 16


# ---------------------------------------------------------------------------
# Fast: step-granular keying
# ---------------------------------------------------------------------------

def _tree(v: float):
    return {"w": jnp.full((4,), float(v), jnp.float32),
            "b": jnp.full((2,), float(v) * 10, jnp.float32)}


def test_step_granular_save_and_resume_keying(tmp_path):
    mgr = CheckpointManager(
        str(tmp_path / "ckpt"), save_every_steps=2, async_save=False,
        max_to_keep=10,
    )
    assert mgr.step_granular
    assert not mgr.save_step(1, _tree(1))   # not due
    assert mgr.save_step(2, _tree(2))       # due every 2
    assert not mgr.save_step(3, _tree(3))
    # epoch boundary (epoch 0 of a 4-step epoch) forces the save under
    # its global-step key
    assert mgr.save_epoch_end(0, _tree(4), global_step=4)
    # boundary coinciding with an already-saved due step is idempotent
    assert mgr.save_step(4, _tree(4)) is False
    assert mgr.save_step(6, _tree(6))
    mgr.close()

    mgr2 = CheckpointManager(
        str(tmp_path / "ckpt"), save_every_steps=2, async_save=False
    )
    state, epoch, skip = mgr2.maybe_restore_at(_tree(0), steps_per_epoch=4)
    assert (epoch, skip) == (1, 2)  # key 6 on a 4-step epoch
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full(4, 6.0))
    mgr2.close()


def test_epoch_mode_unchanged_and_skipless(tmp_path):
    """save_epoch_end without step granularity keeps the legacy epoch
    keying and maybe_restore_at always reports skip_steps == 0."""
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    assert not mgr.step_granular
    assert mgr.save_step(5, _tree(5)) is False  # step saves are inert
    assert mgr.save_epoch_end(0, _tree(1), global_step=4)
    state, epoch, skip = mgr.maybe_restore_at(_tree(0), steps_per_epoch=4)
    assert (epoch, skip) == (1, 0)
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full(4, 1.0))
    mgr.close()


# ---------------------------------------------------------------------------
# Fast: corrupt-latest fallback (the partial-write fault)
# ---------------------------------------------------------------------------

def test_corrupt_latest_checkpoint_falls_back(tmp_path):
    """A truncated newest checkpoint (preemption mid-write, rehearsed by
    scripts/faultgen.py corrupt-latest) must not kill the resume: the
    manager falls back to the previous valid step."""
    ckpt_dir = str(tmp_path / "ckpt")
    mgr = CheckpointManager(
        ckpt_dir, save_every_steps=2, async_save=False, max_to_keep=10
    )
    assert mgr.save_step(2, _tree(2))
    assert mgr.save_step(4, _tree(4))
    mgr.close()

    # corrupt through the CLI so the tool itself is exercised
    res = subprocess.run(
        [sys.executable, "scripts/faultgen.py", "corrupt-latest", ckpt_dir],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 0, res.stderr
    assert "truncated checkpoint step 4" in res.stdout

    mgr2 = CheckpointManager(
        ckpt_dir, save_every_steps=2, async_save=False
    )
    state, epoch, skip = mgr2.maybe_restore_at(_tree(0), steps_per_epoch=4)
    assert (epoch, skip) == (0, 2)  # fell back from 4 to 2
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full(4, 2.0))
    mgr2.close()

    # every checkpoint corrupt -> clean cold start, not a crash
    from distributeddeeplearning_tpu import faults

    shutil.rmtree(os.path.join(ckpt_dir, "4"))  # only step 2 remains...
    faults.corrupt_latest_checkpoint(ckpt_dir)  # ...and now it's corrupt
    mgr3 = CheckpointManager(
        ckpt_dir, save_every_steps=2, async_save=False
    )
    state, epoch, skip = mgr3.maybe_restore_at(_tree(0), steps_per_epoch=4)
    assert (epoch, skip) == (0, 0)
    np.testing.assert_array_equal(np.asarray(state["w"]), np.zeros(4))
    mgr3.close()


# ---------------------------------------------------------------------------
# Fast-ish: in-process mid-epoch resume equivalence
# ---------------------------------------------------------------------------

def _lm_cfg(**kw):
    base = dict(
        model="lm_tiny",
        num_classes=VOCAB,
        batch_size_per_device=2,
        fake_data_length=64,
        epochs=2,
        compute_dtype="float32",
        weight_decay=0.0,
        log_every_steps=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _lm_fit(cfg, mesh8):
    from distributeddeeplearning_tpu.data.synthetic import (
        SyntheticTokenDataset,
    )
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    data = SyntheticTokenDataset(
        length=cfg.fake_data_length,
        global_batch_size=cfg.global_batch_size,
        seq_len=T,
        vocab_size=VOCAB,
    )
    model = get_model(
        "lm_tiny", num_classes=VOCAB, dtype="float32", max_seq_len=T
    )
    return loop.fit(model, cfg, data, mesh=mesh8, add_default_logger=False)


def test_midepoch_resume_is_bitwise_equivalent(tmp_path, mesh8):
    """Simulated preemption: a fully-trained run's checkpoints are rolled
    back to a MID-epoch step key, and a fresh fit resumes there — epoch
    stream re-entered, completed batches skipped — landing on final
    params bitwise-equal to the uninterrupted run."""
    # Reference: uninterrupted, no checkpointing.
    ref = _lm_fit(_lm_cfg(), mesh8)

    # Checkpointed run: steps keyed globally, every save durable.
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = _lm_cfg(
        model_dir=ckpt_dir,
        checkpoint_every_steps=3,
        checkpoint_async=False,
    )
    full = _lm_fit(cfg, mesh8)
    # Checkpointing must not perturb the math to begin with.
    for a, b in zip(
        jax.tree.leaves(jax.device_get(ref.state.params)),
        jax.tree.leaves(jax.device_get(full.state.params)),
    ):
        np.testing.assert_array_equal(a, b)

    # "Preempt at step 6": drop every newer checkpoint (4 steps/epoch,
    # so key 6 is MID-epoch-1: skip 2 of its 4 batches) and resume.
    from distributeddeeplearning_tpu import faults

    steps = faults.checkpoint_steps(ckpt_dir)
    assert 6 in steps, steps
    for s in steps:
        if s > 6:
            shutil.rmtree(os.path.join(ckpt_dir, str(s)))
    resumed = _lm_fit(cfg, mesh8)
    assert resumed.history[0]["epoch_images"] == 32  # 2 of 4 batches left
    for a, b in zip(
        jax.tree.leaves(jax.device_get(ref.state.params)),
        jax.tree.leaves(jax.device_get(resumed.state.params)),
    ):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# Heavy: the ISSUE 4 acceptance runs (2-OS-process worlds)
# ---------------------------------------------------------------------------

def _run_launcher(args, timeout=900):
    return subprocess.run(
        [sys.executable, "launch.py", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=timeout,
    )


def _ft_env_args(tmp_path, engine, **extra):
    env = dict(
        FAKE="True",
        MODEL="resnet18",
        IMAGE_SIZE="8",
        NUM_CLASSES="8",
        BATCHSIZE="2",
        FAKE_DATA_LENGTH="64",
        EPOCHS="2",
        ENGINE=engine,
        CHECKPOINT_ASYNC="0",
        # NOTE: deliberately no COMPILATION_CACHE_DIR — this jax build's
        # persistent cache heap-corrupts (glibc abort) under concurrent
        # multi-process write+reread of one cache dir, which is exactly
        # the restart pattern. Observed as SIGABRT ("corrupted
        # double-linked list") in the relaunched world; reproducible by
        # adding the knob back here WITH the supervisor's guard disabled.
        # launch_supervised now auto-suffixes the dir per restart attempt
        # (<dir>-r<k>) so configured caches no longer hit this
        # (tests/test_faults.py::test_supervisor_suffixes_cache_dir).
    )
    env.update(extra)
    out = []
    for k, v in env.items():
        out += ["--env", f"{k}={v}"]
    return out


def _shas(out):
    return dict(re.findall(r"FT_PARAMS_SHA (\d+) ([0-9a-f]{64})", out))


@pytest.mark.parametrize("engine", ["dp", "pjit"])
def test_resume_equivalence_across_supervised_restart(engine, tmp_path):
    """The acceptance criterion: FAULT_PLAN SIGKILLs process 1 after
    step 3 of a 2-process world; the supervisor restarts it, the world
    resumes from the step-granular checkpoint mid-epoch, and the final
    params are BITWISE-equal to an uninterrupted run — under both the
    shard_map dp engine and the GSPMD pjit engine."""
    base = [
        "--num-processes", "2",
        "--devices-per-process", "4",
        "--platform", "cpu",
        "--timeout", "540",
    ]
    # Run A: uninterrupted reference (no checkpointing at all).
    res_a = _run_launcher(
        [*base, *_ft_env_args(tmp_path, engine), "tests/_ft_child.py"]
    )
    out_a = res_a.stdout + res_a.stderr
    assert res_a.returncode == 0, out_a[-4000:]
    shas_a = _shas(out_a)
    assert set(shas_a) == {"0", "1"}, out_a[-2000:]
    assert shas_a["0"] == shas_a["1"]  # replicated params agree

    # Run B: step checkpoints + SIGKILL of rank 1 after step 3, under
    # the restart supervisor.
    res_b = _run_launcher(
        [
            *base,
            "--max-restarts", "1",
            "--restart-backoff", "0.1",
            *_ft_env_args(
                tmp_path, engine,
                MODEL_DIR=str(tmp_path / "b_ckpt"),
                CHECKPOINT_EVERY_STEPS="1",
                FAULT_PLAN="kill:step=3,rank=1",
            ),
            "tests/_ft_child.py",
        ]
    )
    out_b = res_b.stdout + res_b.stderr
    assert res_b.returncode == 0, out_b[-4000:]
    assert "supervisor: attempt 0 failed (rc=-9, signal_SIGKILL" in out_b
    # the relaunched world resumed MID-epoch from the step checkpoint
    assert "resuming from epoch 0 step 3" in out_b, out_b[-4000:]
    shas_b = _shas(out_b)
    assert set(shas_b) == {"0", "1"}, out_b[-2000:]
    assert shas_b == shas_a, (shas_a, shas_b)  # bitwise-equal final params


def test_corrupt_latest_falls_back_across_topologies(tmp_path, devices):
    """Corrupt-checkpoint fallback under RESHARDING: step checkpoints
    written from the 8-device mesh, the newest truncated (preemption
    mid-write), then restored at a DIFFERENT device count — the manager
    must fall back past the corrupt step onto the new topology, manifest
    intact."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributeddeeplearning_tpu import faults
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.checkpoint import (
        build_manifest,
    )

    mesh8 = create_mesh(devices=devices)
    ckpt_dir = str(tmp_path / "ckpt")

    def tree(mesh, v):
        return {
            "w": jax.device_put(
                jnp.full((16,), float(v), jnp.float32),
                NamedSharding(mesh, P("data")),
            ),
            "b": jax.device_put(
                jnp.full((4,), float(v) * 10, jnp.float32),
                NamedSharding(mesh, P()),
            ),
        }

    mgr = CheckpointManager(
        ckpt_dir, save_every_steps=2, async_save=False, max_to_keep=10
    )
    for s in (2, 4):
        assert mgr.save_step(
            s, tree(mesh8, s),
            manifest=build_manifest(
                global_step=s, steps_per_epoch=4, effective_batch=16,
                world_size=8,
            ),
        )
    mgr.close()
    assert faults.corrupt_latest_checkpoint(ckpt_dir)

    for n_dev in (1, 4):
        sub = create_mesh(devices=devices[:n_dev])
        mgr2 = CheckpointManager(
            ckpt_dir, save_every_steps=2, async_save=False
        )
        state, epoch, skip = mgr2.maybe_restore_at(
            tree(sub, 0), steps_per_epoch=4
        )
        assert (epoch, skip) == (0, 2)  # fell back from 4 to 2
        np.testing.assert_array_equal(
            np.asarray(state["w"]), np.full(16, 2.0)
        )
        assert mgr2.last_manifest["global_step"] == 2
        assert mgr2.last_manifest["world_size"] == 8
        assert set(jax.tree.leaves(state)[0].sharding.device_set) <= set(
            sub.devices.flat
        )
        mgr2.close()


# ---------------------------------------------------------------------------
# Heavy: the ISSUE 11 elastic drill (2-OS-process world, shrink -> grow)
# ---------------------------------------------------------------------------

def _losses(out):
    """rank-0 FT_EPOCH_LOSS lines -> {global_step: loss} (hex-exact)."""
    return {
        int(s): float.fromhex(v)
        for r, s, v in re.findall(
            r"FT_EPOCH_LOSS (\d+) (\d+) (\S+)", out
        )
        if r == "0"
    }


def test_elastic_supervised_shrink_grow_e2e(tmp_path):
    """The ISSUE 11 acceptance drill: a supervised 2-process lm_tiny
    world loses rank 1 mid-epoch (shrink preemption). The elastic
    supervisor relaunches at world 1 with BATCHSIZE/ACCUM_STEPS doubled
    (effective batch constant, LR world pinned), re-sharding from the
    topology-independent step checkpoint. The shrunken world announces
    restored capacity at a later step; the grow poller stops it and the
    full-size world resumes, re-sharding again. The post-resume loss
    trajectory and final params match an uninterrupted fixed-world run
    at f32-ULP (the accum rescale re-associates reductions — the
    documented ISSUE-3 semantics; bitwise is mathematically
    unavailable)."""
    base = [
        "--num-processes", "2",
        "--devices-per-process", "2",
        "--platform", "cpu",
        "--timeout", "540",
    ]
    env = dict(
        MODEL="lm_tiny",
        NUM_CLASSES="64",
        SEQ_LEN="16",
        COMPUTE_DTYPE="float32",
        WEIGHT_DECAY="0",
        BATCHSIZE="2",
        FAKE_DATA_LENGTH="64",   # global batch 8 -> 8 steps/epoch
        EPOCHS="2",
        ENGINE="dp",
        CHECKPOINT_ASYNC="0",
        DATA_TOPOLOGY="global",  # world-size-independent stream
    )

    def env_args(extra):
        out = []
        for k, v in {**env, **extra}.items():
            out += ["--env", f"{k}={v}"]
        return out

    # Run A: uninterrupted fixed world.
    res_a = _run_launcher(
        [*base, *env_args({"FT_PARAMS_OUT": str(tmp_path / "ref.npz")}),
         "tests/_ft_child.py"]
    )
    out_a = res_a.stdout + res_a.stderr
    assert res_a.returncode == 0, out_a[-4000:]
    losses_a = _losses(out_a)
    assert set(losses_a) == {8, 16}, out_a[-2000:]

    # Run B: the elastic drill. shrink after step 3 (mid-epoch-0),
    # capacity restored once the shrunken world completes step 6.
    res_b = _run_launcher(
        [
            *base,
            "--max-restarts", "2",
            "--restart-backoff", "0.1",
            "--elastic",
            "--min-world-size", "1",
            "--grow-check-every-s", "0.2",
            "--obs-dir", str(tmp_path / "run"),
            *env_args({
                "MODEL_DIR": str(tmp_path / "b_ckpt"),
                "CHECKPOINT_EVERY_STEPS": "1",
                "CHECKPOINT_KEEP": "30",
                "FAULT_PLAN": (
                    "shrink:step=3,rank=1,ranks=1;restore_capacity:step=6"
                ),
                "FT_PARAMS_OUT": str(tmp_path / "elastic.npz"),
            }),
            "tests/_ft_child.py",
        ]
    )
    out_b = res_b.stdout + res_b.stderr
    assert res_b.returncode == 0, out_b[-4000:]
    # the shrink was classified and the world relaunched HALVED with the
    # integer rescale announced
    assert "rc=-9, signal_SIGKILL" in out_b
    assert (
        "supervisor: elastic world 1/2 processes — BATCHSIZE 2->4, "
        "ACCUM_STEPS 1->2" in out_b
    ), out_b[-4000:]
    # the shrunken world resumed MID-epoch from the step checkpoint
    assert re.search(r"resuming from epoch 0 step [3-9]", out_b), out_b[-4000:]
    # grow-back: coordinated resize stop, full world resumed
    assert "supervisor: world resize 1 -> 2" in out_b, out_b[-4000:]
    assert "no restart budget consumed" in out_b

    # Oracle: the post-resume trajectory matches the uninterrupted run
    # at f32-ULP (final full epoch is entirely post-resume)...
    losses_b = _losses(out_b)
    assert 16 in losses_b, (losses_b, out_b[-2000:])
    np.testing.assert_allclose(
        losses_b[16], losses_a[16], rtol=1e-4, atol=1e-6
    )
    # ...and so do the final params (both ranks bitwise-agree on them
    # inside run B — the grow-back restore is bitwise-faithful).
    shas_b = _shas(out_b)
    assert set(shas_b) == {"0", "1"} and shas_b["0"] == shas_b["1"]
    ref_np = np.load(str(tmp_path / "ref.npz"))
    ela_np = np.load(str(tmp_path / "elastic.npz"))
    assert set(ref_np.files) == set(ela_np.files)
    for k in ref_np.files:
        np.testing.assert_allclose(
            ela_np[k], ref_np[k], rtol=2e-4, atol=2e-7, err_msg=k
        )
    # supervisor record carries the per-attempt world sizes
    recs = [
        json.loads(ln)
        for ln in open(tmp_path / "run" / "events-supervisor.jsonl")
    ]
    starts = [
        r["labels"]["world_size"] for r in recs
        if r.get("name") == "attempt_start"
    ]
    assert starts[:2] == [2, 1] and starts[-1] == 2, starts


def test_nan_guard_halts_supervised_world(tmp_path):
    """NaN-injected loss halts the supervised world with the distinct
    non-retryable exit code: no restart is attempted, rc is 121."""
    res = _run_launcher(
        [
            "--num-processes", "1",
            "--devices-per-process", "4",
            "--platform", "cpu",
            "--timeout", "540",
            "--max-restarts", "2",
            "--restart-backoff", "0.1",
            *_ft_env_args(
                tmp_path, "dp",
                EPOCHS="1",
                FAULT_PLAN="nan:step=2",
            ),
            "tests/_ft_child.py",
        ]
    )
    out = res.stdout + res.stderr
    assert res.returncode == 121, out[-4000:]
    assert "non-finite loss" in out
    assert "non-retryable" in out
    assert "restarting in" not in out  # the guard's code burns no restarts
