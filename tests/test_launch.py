"""Launcher tests — including TRUE multi-process (2 OS processes) runs.

The reference's distributed logic is smoke-tested by ``mpirun -np 2 -H
localhost:2`` inside the framework container (``Horovod*/00_CreateImage
AndTest.ipynb`` cells 6-10, SURVEY.md §4.2). These tests do the same for
the TPU build: ``launch.py --num-processes 2`` forks two real python
processes that rendezvous via ``jax.distributed.initialize`` on a forced
CPU backend and execute the genuinely multi-host code paths
(``make_array_from_process_local_data``, ``broadcast_one_to_all``,
per-process TFRecord sharding) that the in-process 8-device suite cannot.
"""

import io
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from distributeddeeplearning_tpu.launch import (
    _child_env,
    _parse_env_args,
    build_pod_command,
    find_free_port,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Unit: command construction
# ---------------------------------------------------------------------------

def test_find_free_port():
    p = find_free_port()
    assert isinstance(p, int) and 0 < p < 65536


def test_parse_env_args():
    assert _parse_env_args(["A=1", "B=x=y"]) == {"A": "1", "B": "x=y"}
    with pytest.raises(SystemExit):
        _parse_env_args(["NOEQUALS"])


def test_child_env_contract():
    env = _child_env(
        {"XLA_FLAGS": "--xla_force_host_platform_device_count=8 --foo"},
        coordinator="127.0.0.1:1234",
        num_processes=2,
        process_id=1,
        platform="cpu",
        devices_per_process=4,
        extra_env={"FAKE": "True"},
    )
    assert env["DDL_COORDINATOR"] == "127.0.0.1:1234"
    assert env["DDL_NUM_PROCESSES"] == "2"
    assert env["DDL_PROCESS_ID"] == "1"
    assert env["DDL_PLATFORM"] == "cpu"
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["FAKE"] == "True"
    # stale forced-device-count flag replaced, other flags kept
    assert env["XLA_FLAGS"].count("--xla_force_host_platform_device_count") == 1
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert "--foo" in env["XLA_FLAGS"]


def test_build_pod_command():
    cmd = build_pod_command(
        "examples/imagenet_keras_tpu.py",
        ["--flag"],
        tpu="v5e-64-pod",
        zone="us-west4-a",
        project="proj",
        env={"FAKE": "True"},
    )
    joined = " ".join(cmd)
    assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh"]
    assert "v5e-64-pod" in cmd
    assert "--worker=all" in cmd
    assert "--project=proj" in joined
    # remote command exports DISTRIBUTED=True (autodetect path) + user env
    remote = [c for c in cmd if c.startswith("--command=")][0]
    assert "DISTRIBUTED=True" in remote
    assert "FAKE=True" in remote
    assert "python3 -u examples/imagenet_keras_tpu.py" in remote


# ---------------------------------------------------------------------------
# Integration: real 2-process worlds
# ---------------------------------------------------------------------------

def _write_tfrecords(out_dir: str, n_files: int = 4, per_file: int = 8) -> str:
    """Write tiny JPEG TFRecord shards with globally-unique labels 0..N-1."""
    import tensorflow as tf
    from PIL import Image

    label = 0
    for f in range(n_files):
        path = os.path.join(out_dir, f"train-{f:05d}.tfrecord")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_file):
                arr = np.random.RandomState(label).randint(
                    0, 255, (8, 8, 3), np.uint8
                )
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, format="JPEG")
                ex = tf.train.Example(
                    features=tf.train.Features(
                        feature={
                            "image/encoded": tf.train.Feature(
                                bytes_list=tf.train.BytesList(value=[buf.getvalue()])
                            ),
                            "image/class/label": tf.train.Feature(
                                int64_list=tf.train.Int64List(value=[label])
                            ),
                        }
                    )
                )
                w.write(ex.SerializeToString())
                label += 1
    return os.path.join(out_dir, "train-*.tfrecord")


def _run_launcher(args, timeout=600):
    return subprocess.run(
        [sys.executable, "launch.py", *args],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_two_process_world(tmp_path):
    """2 OS processes: rendezvous, collectives, global-array DP step,
    per-process TFRecord sharding — the mpirun -np 2 smoke equivalent."""
    pattern = _write_tfrecords(str(tmp_path))
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--devices-per-process", "4",
            "--platform", "cpu",
            "--timeout", "540",
            "tests/_mp_child.py", pattern,
        ]
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "MP_CHILD_OK 0" in out, out[-4000:]
    assert "MP_CHILD_OK 1" in out, out[-4000:]
    assert "[0] " in out and "[1] " in out  # rank-tagged streaming


def test_two_process_keras_frontend_end_to_end():
    """The VERDICT done-criterion: launch.py -n 2 trains the Keras-style
    front-end example on one host (synthetic data, tiny shapes)."""
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--devices-per-process", "4",
            "--platform", "cpu",
            "--timeout", "540",
            "--env", "FAKE=True",
            "--env", "FAKE_DATA_LENGTH=128",
            "--env", "EPOCHS=1",
            "--env", "BATCHSIZE=4",
            "--env", "IMAGE_SIZE=32",
            "--env", "NUM_CLASSES=8",
            "--env", "MODEL=resnet18",
            "examples/imagenet_keras_tpu.py",
        ]
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "images/sec" in out, out[-4000:]


def test_child_failure_terminates_world(tmp_path):
    """All-or-nothing exit semantics: one failing rank kills the job
    promptly (no hang waiting on the healthy rank's sleep)."""
    script = tmp_path / "failer.py"
    script.write_text(
        textwrap.dedent(
            """
            import os, sys, time
            if os.environ["DDL_PROCESS_ID"] == "1":
                sys.exit(3)
            time.sleep(120)
            """
        )
    )
    res = _run_launcher(
        ["--num-processes", "2", "--timeout", "90", str(script)], timeout=110
    )
    assert res.returncode == 3, (res.returncode, res.stdout[-2000:])


def test_dry_run_modes():
    res = _run_launcher(["--dry-run", "-n", "4", "script.py"])
    assert res.returncode == 0 and "4 local processes" in res.stdout
    res = _run_launcher(
        ["--tpu", "pod", "--zone", "us-west4-a", "--dry-run", "script.py"]
    )
    assert res.returncode == 0
    assert "gcloud compute tpus tpu-vm ssh" in res.stdout
    assert "--worker=all" in res.stdout


def test_hang_watchdog_kills_silent_world(tmp_path):
    """Failure detection the reference lacks: a world whose processes are
    alive but silent (deadlocked collective) is declared hung and killed
    with exit 125."""
    script = tmp_path / "hang.py"
    script.write_text(
        "import time\nprint('alive', flush=True)\ntime.sleep(300)\n"
    )
    t0 = time.time()
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--hang-timeout", "4",
            "--timeout", "120",
            str(script),
        ],
        timeout=90,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 125, out[-2000:]
    assert "declaring the world hung" in out, out[-2000:]
    assert time.time() - t0 < 60  # watchdog fired, not the 120s timeout


# ---------------------------------------------------------------------------
# Observability: --obs-dir events, host-0 merge, flight recorder (ISSUE 2)
# ---------------------------------------------------------------------------

_OBS_CHILD = textwrap.dedent(
    """
    import json, os, sys, time
    from distributeddeeplearning_tpu import obs

    bus = obs.configure_from_env()
    rank = os.environ["DDL_PROCESS_ID"]
    with bus.span("work", rank=rank):
        time.sleep(0.05)
    bus.counter("things", 3)
    bus.flush()
    bus.point("unflushed_tail")  # ring-only: the flight dump's proof
    print("OBS_CHILD_OK", rank, flush=True)
    if rank == "1" and os.environ.get("HANG"):
        time.sleep(300)  # silent: the watchdog must kill us
    """
)


def test_obs_run_produces_merged_events_and_report(tmp_path):
    """The ISSUE 2 done-criterion: a 2-OS-process launch.py run writes
    per-process events.jsonl, the launcher (host 0) merges them, and
    scripts/obs_report.py renders a report from the run dir."""
    script = tmp_path / "obs_child.py"
    script.write_text(_OBS_CHILD)
    obs_dir = tmp_path / "run1"
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--obs-dir", str(obs_dir),
            "--timeout", "120",
            "--env", "JAX_PLATFORMS=cpu",
            str(script),
        ],
        timeout=180,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "OBS_CHILD_OK 0" in out and "OBS_CHILD_OK 1" in out
    # per-process event files + the launcher's own lifecycle file
    assert (obs_dir / "events-p0.jsonl").exists()
    assert (obs_dir / "events-p1.jsonl").exists()
    assert (obs_dir / "events-launcher.jsonl").exists()
    # host-0 merge ran at world exit
    merged = obs_dir / "events.jsonl"
    assert merged.exists(), out[-2000:]
    recs = [json.loads(ln) for ln in open(merged)]
    metas = [r for r in recs if r["kind"] == "meta"]
    assert {str(m["p"]) for m in metas} == {"0", "1", "launcher"}
    # one shared run id across the whole world (launcher-minted)
    assert len({m["run"] for m in metas}) == 1
    names = {r["name"] for r in recs if r["kind"] != "meta"}
    assert {"rendezvous", "child_start", "child_exit", "world_exit",
            "work", "things"} <= names
    walls = [r["wall"] for r in recs if "wall" in r]
    assert walls == sorted(walls)  # one consistent timeline

    # ...and the report CLI renders it
    rep = subprocess.run(
        [sys.executable, "scripts/obs_report.py", str(obs_dir)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "work" in rep.stdout and "timeline" in rep.stdout


def test_watchdog_accepts_telemetry_as_liveness(tmp_path):
    """Live-plane liveness (ISSUE 7): a process that prints NOTHING but
    keeps emitting bus events (flushed by OBS_FLUSH_EVERY_S) must not be
    declared hung — the watchdog consumes event-file growth as a
    heartbeat. The control case (same silence, no events) is
    test_hang_watchdog_kills_silent_world."""
    script = tmp_path / "silent_worker.py"
    script.write_text(textwrap.dedent(
        """
        import time
        from distributeddeeplearning_tpu import obs

        bus = obs.configure_from_env()
        for i in range(18):          # ~7.2s of stdout silence
            bus.point("tick", i=i)
            time.sleep(0.4)
        bus.flush()
        """
    ))
    obs_dir = tmp_path / "run-liveness"
    res = _run_launcher(
        [
            "--num-processes", "1",
            "--obs-dir", str(obs_dir),
            "--hang-timeout", "6",   # > child import time, < its runtime
            "--timeout", "120",
            "--env", "JAX_PLATFORMS=cpu",
            "--env", "OBS_FLUSH_EVERY_S=0.5",
            str(script),
        ],
        timeout=180,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "declaring the world hung" not in out


def test_obs_killed_child_leaves_flight_dump(tmp_path):
    """Watchdog kill (SIGTERM) = preemption rehearsal: the hung child's
    flight-recorder ring reaches disk with its last events — including
    ones never flushed to the normal file — and the launcher records
    the watchdog fire; merge still happens on the failure path."""
    script = tmp_path / "obs_child.py"
    script.write_text(_OBS_CHILD)
    obs_dir = tmp_path / "run2"
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--obs-dir", str(obs_dir),
            "--hang-timeout", "6",
            "--timeout", "120",
            "--env", "JAX_PLATFORMS=cpu",
            "--env", "HANG=1",
            str(script),
        ],
        timeout=180,
    )
    out = res.stdout + res.stderr
    assert res.returncode == 125, out[-4000:]
    dump = obs_dir / "flight-p1.jsonl"
    assert dump.exists(), out[-2000:]
    recs = [json.loads(ln) for ln in open(dump)]
    assert recs[0]["kind"] == "flight_meta"
    assert recs[0]["reason"] == "sigterm"
    names = [r["name"] for r in recs[1:]]
    assert "work" in names
    assert "unflushed_tail" in names  # the ring caught the unflushed tail
    # launcher-side record of WHY the world died, merged and all
    launcher_events = [
        json.loads(ln) for ln in open(obs_dir / "events-launcher.jsonl")
    ]
    assert any(r.get("name") == "watchdog_fired" for r in launcher_events)
    assert (obs_dir / "events.jsonl").exists()


@pytest.mark.parametrize(
    "engine_env",
    [
        ("sp", [("MESH_AXES", "data,seq"), ("MESH_SHAPE", "2,4")]),
        ("pp", [("MESH_AXES", "data,pipe"), ("MESH_SHAPE", "2,4"),
                ("PP_MICROBATCHES", "2"), ("PP_SCHEDULE", "1f1b")]),
    ],
    ids=["sp", "pp-1f1b"],
)
def test_two_process_engine_contract(engine_env):
    """ENGINE=sp / ENGINE=pp across 2 REAL OS processes: the ring/pipe
    ppermute hops cross the process boundary over the distributed
    backend — the multi-host story for the round-3 engine contract."""
    engine, extra = engine_env
    env_args = []
    for k, v in [("FAKE_DATA_LENGTH", "64"), ("EPOCHS", "1"),
                 ("BATCHSIZE", "2"), ("SEQ_LEN", "16"), ("VOCAB", "64"),
                 ("MODEL", "lm_tiny"), ("ENGINE", engine), *extra]:
        env_args += ["--env", f"{k}={v}"]
    res = _run_launcher(
        [
            "--num-processes", "2",
            "--devices-per-process", "4",
            "--platform", "cpu",
            "--timeout", "540",
            *env_args,
            "examples/lm_synthetic_tpu.py",
        ]
    )
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-4000:]
    assert "images/sec" in out, out[-4000:]
