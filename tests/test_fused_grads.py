"""Oracle tests for the fused dW+db backward (ops/pallas/fused_grads.py).

Interpret mode on the CPU backend — same protocol as the other kernel
oracles (tests/test_depthwise.py, test_fused_block.py): exact math
against the XLA reference, tolerances only for f32 partial-sum
reordering across contraction blocks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.ops.pallas.fused_grads import (
    bias_dense,
    matmul_dw_db,
)


@pytest.mark.parametrize(
    "n,k,m",
    [
        (64, 128, 128),     # single tile
        (600, 128, 256),    # ragged N tail (600 = 512 + 88)
        (1024, 256, 768),   # multi-M-tile
        (96, 384, 512),     # n < bn path
    ],
)
def test_matmul_dw_db_matches_xla(n, k, m):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, k).astype(np.float32), jnp.bfloat16)
    g = jnp.asarray(rng.randn(n, m).astype(np.float32), jnp.bfloat16)
    dw, db = matmul_dw_db(x, g, interpret=True)
    ref_dw = jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    ref_db = jnp.sum(g.astype(jnp.float32), axis=0)
    assert dw.dtype == jnp.float32 and db.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(dw), np.asarray(ref_dw), rtol=1e-5, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(db), np.asarray(ref_db), rtol=1e-5, atol=1e-4
    )


def test_vmem_budget_enforced_falls_back_to_xla():
    """ADVICE r5: when no lane-aligned tile keeps the f32 accumulator
    [k, bm] inside the VMEM budget (huge K, or a wide un-128-aligned
    head), matmul_dw_db must take the stock XLA path — correct numbers,
    no overflowing kernel — instead of clamping bm and shipping it."""
    from distributeddeeplearning_tpu.ops.pallas import fused_grads as fg

    assert not fg._fits_vmem(20_000, fg._pick_bm(256, 20_000))
    assert not fg._fits_vmem(20_000, fg._pick_bm(200, 20_000))  # bm=m path
    assert fg._fits_vmem(128, fg._pick_bm(256, 128))

    rng = np.random.RandomState(3)
    n, k, m = 8, 20_000, 200
    x = jnp.asarray(rng.randn(n, k).astype(np.float32), jnp.bfloat16)
    g = jnp.asarray(rng.randn(n, m).astype(np.float32), jnp.bfloat16)
    # interpret=False is safe here BECAUSE the fallback is pure XLA; a
    # pallas_call would need interpret mode on CPU.
    dw, db = matmul_dw_db(x, g, interpret=False)
    ref_dw = jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    ref_db = jnp.sum(g.astype(jnp.float32), axis=0)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(ref_dw), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(db), np.asarray(ref_db), rtol=1e-5)


def test_bias_dense_forward_matches_dense():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 17, 128).astype(np.float32))
    w = jnp.asarray(rng.randn(128, 256).astype(np.float32))
    b = jnp.asarray(rng.randn(256).astype(np.float32))
    y = bias_dense(x, w, b, jnp.bfloat16, True)
    ref = (
        jnp.dot(x.astype(jnp.bfloat16), w.astype(jnp.bfloat16))
        + b.astype(jnp.bfloat16)
    )
    np.testing.assert_array_equal(np.asarray(y), np.asarray(ref))


def test_bias_dense_grads_match_reference():
    # f32 compute so both sides share accumulation semantics: the CPU
    # reference's bf16 dot accumulates in bf16, while the kernel always
    # accumulates f32 (MXU semantics) — with bf16 compute the KERNEL is
    # the more precise side and "mismatch" just measures the reference's
    # rounding. bf16 in/out numerics are covered by
    # test_matmul_dw_db_matches_xla against an explicit-f32-accumulation
    # reference.
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(6, 37, 128).astype(np.float32))
    w = jnp.asarray(rng.randn(128, 384).astype(np.float32))
    b = jnp.asarray(rng.randn(384).astype(np.float32))

    def fused_loss(x, w, b):
        return jnp.sum(bias_dense(x, w, b, jnp.float32, True) ** 2)

    def ref_loss(x, w, b):
        y = jnp.dot(x, w) + b
        return jnp.sum(y ** 2)

    got = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, b)
    ref = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
    for got_g, ref_g, name in zip(got, ref, ("dx", "dw", "db")):
        assert got_g.dtype == ref_g.dtype, name
        np.testing.assert_allclose(
            np.asarray(got_g),
            np.asarray(ref_g),
            rtol=1e-4, atol=1e-3,  # block-wise f32 partial-sum reordering
            err_msg=name,
        )


def test_fused_dense_grad_step_matches_stock(monkeypatch, devices):
    """ONE dp train step of ViT-ti with FUSED_DENSE_GRAD=1 equals the
    stock step (single-step oracle — multi-step is chaotic, see the
    per-replica-BN lesson). f32 compute keeps both sides' accumulation
    semantics identical on CPU."""
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.parallel.mesh import data_parallel_mesh
    from distributeddeeplearning_tpu.training import (
        create_train_state,
        make_train_step,
    )
    from distributeddeeplearning_tpu.training.train_step import replicate_state

    rng = np.random.RandomState(3)
    images = rng.randn(16, 16, 16, 3).astype(np.float32)
    labels = rng.randint(0, 8, size=(16,)).astype(np.int32)
    results = {}
    for flag in ("", "1"):
        monkeypatch.setenv("FUSED_DENSE_GRAD", flag)
        from distributeddeeplearning_tpu.models.vit import ViT

        cfg = TrainConfig(num_classes=8, image_size=16, batch_size_per_device=2)
        model = ViT(variant="ti", patch_size=16, num_classes=8,
                    dtype=jnp.float32)
        mesh = data_parallel_mesh()
        tx = optax.sgd(0.1, momentum=0.9)
        state = replicate_state(
            create_train_state(model, cfg, tx, input_shape=(1, 16, 16, 3)),
            mesh,
        )
        step = make_train_step(model, tx, mesh, cfg, donate_state=False)
        new_state, metrics = step(state, shard_batch((images, labels), mesh))
        results[flag] = (
            float(metrics["loss"]),
            np.asarray(jax.tree.leaves(new_state.params)[0], np.float32),
        )
    np.testing.assert_allclose(results["1"][0], results[""][0], rtol=1e-5)
    np.testing.assert_allclose(
        results["1"][1], results[""][1], rtol=1e-4, atol=1e-5
    )


def test_fused_flag_falls_back_under_pjit_engine(monkeypatch, devices):
    """FUSED_DENSE_GRAD=1 under the GSPMD engine must NOT route through
    the Pallas custom call (opaque to the SPMD partitioner): the pjit
    traces are wrapped in gspmd_trace() and _FusedGradDense falls back
    to the stock XLA dense — the step must simply work on a TP mesh."""
    import optax

    monkeypatch.setenv("FUSED_DENSE_GRAD", "1")
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.data.pipeline import shard_batch
    from distributeddeeplearning_tpu.models.vit import LOGICAL_RULES, ViT
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.pjit_step import (
        create_sharded_train_state,
        make_pjit_train_step,
    )

    mesh = create_mesh(axes=("data", "model"), shape=(4, 2))
    cfg = TrainConfig(num_classes=16, image_size=16, batch_size_per_device=2)
    model = ViT(variant="ti", patch_size=16, num_classes=16, dtype=jnp.bfloat16)
    tx = optax.sgd(0.1)
    state = create_sharded_train_state(
        model, cfg, tx, mesh, LOGICAL_RULES, input_shape=(1, 16, 16, 3)
    )
    rng = np.random.RandomState(0)
    step = make_pjit_train_step(model, tx, mesh, cfg, donate_state=False)
    with mesh:
        batch = shard_batch(
            (
                rng.randn(8, 16, 16, 3).astype(np.float32),
                rng.randint(0, 16, size=(8,)).astype(np.int32),
            ),
            mesh,
        )
        _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
