"""Train/serve colocation arbiter oracles (serving/arbiter.py).

All jax-free (the arbiter runs in the supervisor/controller process):

* the divisor shrink ladder + the ARBITER_* env contract;
* shrink gating — the brownout ladder must be EXHAUSTED and the burn
  sustained before training pays (brownout → shed → shrink, the
  declared escalation order of docs/ROBUSTNESS.md);
* the lease API — grant/deny/idempotency, the reclaim priority
  (training reclaiming denies new leases), the TTL reaper;
* grow-back hysteresis (calm ticks) and the epoch-boundary reclaim
  hook, with zero-drop sequencing: capacity only restores after the
  LAST lease returns;
* the hardened capacity-file probe — torn/empty/malformed/stale/
  unknown-owner files read as "no change" (never a surprise resize)
  with a ``capacity_file_invalid`` obs point;
* the faultgen ``coloc-drill`` generator + combined-plan ``validate``;
* bench_trend's ``coloc_change`` protocol skip.

The heavy combined fault+chaos storm drill (``make coloc-bench``:
serving surge → ladder exhaustion → arbiter shrink → lease-gated
scale-up → reclaim → zero-drop drain → grow, certified against an
uninterrupted training reference at f32 ULP) runs the real script and
is registered in ``tests/heavy_tests.txt``.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from distributeddeeplearning_tpu import faults, obs
from distributeddeeplearning_tpu.serving.arbiter import (
    ArbiterConfig,
    PoolArbiter,
    _shrink_target,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _snap(pressure=None, burning=False):
    """A synthetic rollup snapshot: the fleet-pressure gauge plus an
    (optionally burning) latency SLO row — the two signals the arbiter
    arbitrates on."""
    return {
        "gauges": {"serve.fleet_pressure": {"value": pressure}},
        "slo": [
            {"objective": "ttft", "stat": "p99", "metric": "serve.ttft",
             "burning": bool(burning)}
        ],
    }


class _Ladder:
    """Stand-in brownout ladder with a settable ``exhausted`` verdict."""

    def __init__(self, exhausted=True):
        self.exhausted = exhausted


def _arbiter(tmp_path, ladder=None, reader=None, **cfg):
    kw = dict(
        pool_devices=8, min_train_world=2, devices_per_replica=4,
        shrink_ticks=2, grow_ticks=3,
    )
    kw.update(cfg)
    return PoolArbiter(
        ArbiterConfig(**kw), str(tmp_path / "capacity.json"),
        reader=reader, ladder=ladder,
    )


# ---------------------------------------------------------------------------
# Shrink ladder + config contract
# ---------------------------------------------------------------------------

def test_shrink_target_walks_the_divisor_ladder():
    assert _shrink_target(8, 8, 1) == 4
    assert _shrink_target(8, 4, 1) == 2
    assert _shrink_target(8, 2, 1) == 1
    assert _shrink_target(8, 2, 2) is None     # floor reached
    assert _shrink_target(8, 8, 5) is None     # no divisor >= floor
    assert _shrink_target(6, 6, 1) == 3        # non-power-of-two pools
    assert _shrink_target(6, 3, 1) == 2


def test_arbiter_config_env_contract_and_validation():
    cfg = ArbiterConfig.from_env({
        "ARBITER_POOL_DEVICES": "8",
        "ARBITER_MIN_TRAIN_WORLD": "4",
        "ARBITER_DEVICES_PER_REPLICA": "4",
        "ARBITER_SHRINK_TICKS": "5",
        "ARBITER_GROW_TICKS": "9",
        "ARBITER_HIGH_PRESSURE": "1.5",
        "ARBITER_LOW_PRESSURE": "0.2",
        "ARBITER_LEASE_TTL_S": "120",
        "ARBITER_WATCH_PREFIX": "serve.",
    })
    assert cfg.pool_devices == 8 and cfg.min_train_world == 4
    assert cfg.shrink_ticks == 5 and cfg.grow_ticks == 9
    assert cfg.lease_ttl_s == 120.0 and cfg.watch_prefix == "serve."
    # overrides beat env
    assert ArbiterConfig.from_env(
        {"ARBITER_POOL_DEVICES": "8"}, pool_devices=4
    ).pool_devices == 4
    with pytest.raises(ValueError):
        ArbiterConfig(pool_devices=0).validate()
    with pytest.raises(ValueError):
        ArbiterConfig(pool_devices=4, min_train_world=5).validate()
    with pytest.raises(ValueError):
        ArbiterConfig(
            pool_devices=4, high_pressure=0.3, low_pressure=0.5
        ).validate()


# ---------------------------------------------------------------------------
# Shrink gating: ladder exhaustion + sustained burn
# ---------------------------------------------------------------------------

def test_shrink_waits_for_ladder_exhaustion(tmp_path):
    """Burn + pressure alone never shrink training while the brownout
    ladder still has stages to apply — serving degrades itself first."""
    ladder = _Ladder(exhausted=False)
    arb = _arbiter(
        tmp_path, ladder=ladder,
        reader=lambda: _snap(pressure=2.0, burning=True),
    )
    for _ in range(10):
        assert arb.tick(now=0.0) is None
    assert arb.train_world == 8 and not arb.decisions
    ladder.exhausted = True
    t = time.time()
    assert arb.tick(now=t) is None            # 1st exhausted+hot obs
    assert arb.tick(now=t) == "shrink"        # 2nd: shrink_ticks met
    assert arb.train_world == 4
    d = arb.decisions[-1]
    assert d["action"] == "shrink"
    assert d["from_world"] == 8 and d["to_world"] == 4
    assert d["objectives"] == "ttft"
    # the capacity file carries the arbiter's reduction + TTL safety net
    cap = str(tmp_path / "capacity.json")
    rec = json.loads(open(cap).read())
    assert rec == {
        "available": 4, "restore_at": pytest.approx(t + 600.0),
        "owner": "arbiter",
    }
    assert faults.probe_capacity(cap, 8) == 4


def test_hot_streak_resets_on_intervening_calm(tmp_path):
    snaps = iter([
        _snap(2.0, True), _snap(0.1, False), _snap(2.0, True),
        _snap(2.0, True),
    ])
    arb = _arbiter(tmp_path, ladder=_Ladder(True),
                   reader=lambda: next(snaps))
    assert arb.tick(now=0.0) is None
    assert arb.tick(now=0.0) is None          # calm tick resets the streak
    assert arb.tick(now=0.0) is None
    assert arb.tick(now=0.0) == "shrink"      # two fresh hot ticks


def test_shrink_respects_floor_and_replica_quantum(tmp_path):
    # min_train_world == pool: there is nothing to give
    arb = _arbiter(
        tmp_path, ladder=_Ladder(True), min_train_world=8,
        reader=lambda: _snap(2.0, True),
    )
    for _ in range(5):
        assert arb.tick(now=0.0) is None
    assert arb.train_world == 8
    # a shrink that frees less than one replica quantum is pointless
    arb = _arbiter(
        tmp_path, ladder=_Ladder(True), min_train_world=4,
        devices_per_replica=8, reader=lambda: _snap(2.0, True),
    )
    for _ in range(5):
        assert arb.tick(now=0.0) is None
    assert arb.train_world == 8


# ---------------------------------------------------------------------------
# Lease API: grant/deny/priority/TTL
# ---------------------------------------------------------------------------

def _shrunk(tmp_path, **cfg):
    arb = _arbiter(
        tmp_path, ladder=_Ladder(True),
        reader=lambda: _snap(2.0, True), **cfg,
    )
    # real wall clock: the shrink write stamps restore_at = now + TTL,
    # and the probe treats a past restore_at as "capacity came back"
    t = time.time()
    arb.tick(now=t)
    assert arb.tick(now=t) == "shrink"
    return arb


def test_lease_grant_deny_and_idempotency(tmp_path):
    arb = _shrunk(tmp_path)
    assert arb.free_devices == 4
    assert arb.request_lease("replica:1", now=0.0) is True
    assert arb.free_devices == 0 and arb.leased_devices == 4
    assert arb.has_lease("replica:1")
    # freed share exhausted: the next claim is denied, with telemetry
    assert arb.request_lease("replica:2", now=0.0) is False
    deny = arb.decisions[-1]
    assert deny["action"] == "lease_deny"
    assert deny["reason"] == "exhausted" and deny["free"] == 0
    # re-asking for a held lease is idempotent, not a second claim
    assert arb.request_lease("replica:1", now=0.0) is True
    assert len(arb.leases) == 1
    assert arb.release_lease("replica:1") is True
    assert arb.free_devices == 4
    assert arb.release_lease("replica:1") is False  # already returned


def test_reclaim_denies_new_leases_until_grow(tmp_path):
    """Priority order: once training wants its devices back, serving
    gets nothing new; the last release restores capacity immediately."""
    hot = [_snap(2.0, True)] * 2
    calm = [_snap(0.1, False)] * 10
    snaps = iter(hot + calm)
    arb = _arbiter(tmp_path, ladder=_Ladder(True),
                   reader=lambda: next(snaps))
    arb.tick(now=0.0)
    assert arb.tick(now=0.0) == "shrink"
    assert arb.request_lease("replica:1", now=0.0)
    # calm ticks: grow_ticks (3) consecutive calm obs -> reclaim (a
    # lease is outstanding, so capacity cannot restore yet)
    assert arb.tick(now=0.0) is None
    assert arb.tick(now=0.0) is None
    assert arb.tick(now=0.0) == "reclaim"
    assert arb.reclaiming
    assert [d["action"] for d in arb.decisions].count("reclaim") == 1
    assert arb.tick(now=0.0) == "reclaim"     # held, not re-announced
    assert [d["action"] for d in arb.decisions].count("reclaim") == 1
    assert arb.request_lease("replica:2", now=0.0) is False
    assert arb.decisions[-1]["reason"] == "reclaiming"
    # zero-drop sequencing: the drain finishes, the lease returns, and
    # ONLY then does full capacity restore
    assert arb.release_lease("replica:1") is True
    assert not arb.reclaiming and arb.train_world == 8
    grow = arb.decisions[-1]
    assert grow["action"] == "grow"
    assert grow["trigger"] == "last_lease_released"
    assert faults.probe_capacity(str(tmp_path / "capacity.json"), 8) == 8


def test_epoch_boundary_reclaims_regardless_of_pressure(tmp_path):
    arb = _shrunk(tmp_path)
    assert arb.request_lease("replica:1", now=0.0)
    # pressure is still hot — the epoch boundary reclaims anyway
    assert arb.epoch_boundary(now=0.0) == "reclaim"
    assert arb.reclaiming
    arb.release_lease("replica:1")
    assert arb.train_world == 8
    assert arb.epoch_boundary(now=0.0) is None  # full world: no-op
    # without leases outstanding the boundary grows immediately
    arb2 = _shrunk(tmp_path)
    assert arb2.epoch_boundary(now=0.0) == "grow"
    assert arb2.decisions[-1]["trigger"] == "epoch_boundary"


def test_lease_ttl_reaps_dead_holders(tmp_path):
    arb = _shrunk(tmp_path, lease_ttl_s=10.0)
    assert arb.request_lease("replica:1", now=100.0)
    arb.tick(now=105.0)   # inside the TTL: lease survives
    assert arb.has_lease("replica:1")
    arb.tick(now=111.0)   # past granted_at + 10s: reaped
    assert not arb.leases
    assert any(
        d["action"] == "lease_expired" and d["owner"] == "replica:1"
        for d in arb.decisions
    )


# ---------------------------------------------------------------------------
# Hardened capacity-file probe: invalid reads as "no change"
# ---------------------------------------------------------------------------

@pytest.fixture
def obs_points(monkeypatch):
    rec = []
    monkeypatch.setattr(
        obs, "point", lambda name, **labels: rec.append((name, labels))
    )
    return rec


def _invalid_reasons(points):
    return [
        lb["reason"] for name, lb in points
        if name == "capacity_file_invalid"
    ]


def test_probe_invalid_files_hold_current_world(tmp_path, obs_points):
    """Torn/empty/malformed capacity files must never resize a running
    world: with ``current`` the probe holds it, and each rejection is a
    ``capacity_file_invalid`` point naming the reason."""
    cap = str(tmp_path / "capacity.json")
    for payload in ('{"available": 4', "", "[1, 2]", '"4"'):
        (tmp_path / "capacity.json").write_text(payload)
        assert faults.probe_capacity(cap, 8, current=4) == 4
        assert faults.probe_capacity(cap, 8) == 8  # no current: full
    assert _invalid_reasons(obs_points) == ["malformed"] * 8
    # a dict with a non-numeric available is the same verdict
    (tmp_path / "capacity.json").write_text('{"available": "soon"}')
    assert faults.probe_capacity(cap, 8, current=2) == 2
    assert _invalid_reasons(obs_points)[-1] == "malformed"
    # a MISSING file stays "full capacity" even with current= — absence
    # is the documented steady state, not corruption
    os.unlink(cap)
    assert faults.probe_capacity(cap, 8, current=4) == 8
    # unreadable (a directory): held, reason=unreadable
    os.mkdir(cap)
    assert faults.probe_capacity(cap, 8, current=4) == 4
    assert _invalid_reasons(obs_points)[-1] == "unreadable"


def test_probe_stale_file_holds_current_world(
    tmp_path, monkeypatch, obs_points
):
    cap = str(tmp_path / "capacity.json")
    faults.write_capacity(cap, 4, owner="fault")
    monkeypatch.setenv(faults.CAPACITY_STALE_ENV, "60")
    assert faults.probe_capacity(cap, 8, current=2) == 4  # fresh
    old = time.time() - 120.0
    os.utime(cap, (old, old))
    assert faults.probe_capacity(cap, 8, current=2) == 2  # stale: hold
    assert faults.probe_capacity(cap, 8) == 8             # no current
    assert _invalid_reasons(obs_points) == ["stale", "stale"]
    monkeypatch.setenv(faults.CAPACITY_STALE_ENV, "0")    # 0 = disabled
    assert faults.probe_capacity(cap, 8, current=2) == 4


def test_probe_unknown_owner_holds_current_world(tmp_path, obs_points):
    cap = str(tmp_path / "capacity.json")
    for owner in faults.CAPACITY_OWNERS:
        faults.write_capacity(cap, 4, owner=owner)
        assert faults.probe_capacity(cap, 8, current=8) == 4
    faults.write_capacity(cap, 4)  # legacy no-owner files stay valid
    assert faults.probe_capacity(cap, 8, current=8) == 4
    assert _invalid_reasons(obs_points) == []
    faults.write_capacity(cap, 4, owner="gremlin")
    assert faults.probe_capacity(cap, 8, current=8) == 8
    assert faults.probe_capacity(cap, 8) == 8
    assert _invalid_reasons(obs_points) == [
        "unknown_owner", "unknown_owner",
    ]


def test_arbiter_capacity_roundtrip_with_probe_current(tmp_path):
    """The arbiter's writes drive launch.py's probe exactly: shrink
    reads back as the reduced world, grow as the full one, and an
    intervening torn write changes nothing."""
    arb = _shrunk(tmp_path)
    cap = str(tmp_path / "capacity.json")
    assert faults.probe_capacity(cap, 8, current=8) == 4
    with open(cap, "w") as fh:
        fh.write('{"available"')   # torn overwrite mid-flight
    assert faults.probe_capacity(cap, 8, current=4) == 4
    arb._grow(trigger="test")
    assert faults.probe_capacity(cap, 8, current=4) == 8
    assert json.loads(open(cap).read())["owner"] == "arbiter"


# ---------------------------------------------------------------------------
# Brownout ladder exhaustion (the arbiter's escalation signal)
# ---------------------------------------------------------------------------

def test_brownout_ladder_exhaustion_signal():
    from distributeddeeplearning_tpu.serving.scheduler import (
        BrownoutLadder,
        parse_brownout_stages,
    )

    burn = {"on": True}

    def reader():
        return {"slo": [
            {"objective": "ttft", "stat": "p99", "metric": "serve.ttft",
             "burning": burn["on"]}
        ] if burn["on"] else []}

    class _Router:
        def apply_brownout_stage(self, stage, on, key=None):
            pass

    ladder = BrownoutLadder(
        parse_brownout_stages("spec_off,max_new:8"), reader=reader,
        refresh_s=0.0, escalate_ticks=1, recover_ticks=1,
    )
    router = _Router()
    assert not ladder.exhausted
    assert ladder.tick(router, 0.0) == "down"
    assert not ladder.exhausted           # stage 2 still unapplied
    assert ladder.tick(router, 0.0) == "down"
    assert ladder.exhausted               # all stages on, still burning
    burn["on"] = False
    ladder.tick(router, 0.0)
    assert not ladder.exhausted           # recovered: burn is out


# ---------------------------------------------------------------------------
# faultgen coloc-drill + combined-plan validate
# ---------------------------------------------------------------------------

def _faultgen(*args):
    return subprocess.run(
        [sys.executable, "scripts/faultgen.py", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_faultgen_coloc_drill_emits_paired_plans(tmp_path):
    res = _faultgen(
        "coloc-drill", "--shrink-step", "6", "--ranks", "1",
        "--restore-step", "10", "--replicas", "2", "--storm-seed", "3",
    )
    assert res.returncode == 0, res.stderr
    lines = res.stdout.strip().splitlines()
    assert lines[0] == (
        "FAULT_PLAN=shrink:step=6,ranks=1;restore_capacity:step=10"
    )
    assert lines[1].startswith("SERVE_CHAOS_PLAN=")
    # both emitted dialects re-validate, separately and combined
    for line in lines:
        v = _faultgen("validate", line.split("=", 1)[1])
        assert v.returncode == 0, v.stderr
    combined = tmp_path / "coloc.plan"
    combined.write_text(res.stdout)
    v = _faultgen("validate", str(combined))
    assert v.returncode == 0, v.stderr
    assert "combined plan (both dialects):" in v.stdout
    assert "shrink" in v.stdout and "crash" in v.stdout


def test_faultgen_validate_rejects_bad_combined_plan(tmp_path):
    bad = tmp_path / "bad.plan"
    bad.write_text(
        "FAULT_PLAN=shrink:step=6,ranks=0\n"
        "SERVE_CHAOS_PLAN=crash:tick=5,replica=0\n"
    )
    assert _faultgen("validate", str(bad)).returncode != 0


# ---------------------------------------------------------------------------
# bench_trend: a re-arbitrated pool is a protocol skip
# ---------------------------------------------------------------------------

def test_bench_trend_coloc_change_is_skip_not_regression(tmp_path):
    from scripts.bench_trend import analyze

    def rec(n, value, coloc=None):
        detail = {"platform": "cpu"}
        if coloc is not None:
            detail["coloc"] = coloc
        path = tmp_path / f"BENCH_r{n:02d}.json"
        path.write_text(json.dumps({
            "n": n, "rc": 0,
            "parsed": {"metric": "lm_coloc_tokens_per_sec",
                       "value": value, "unit": "tokens/sec",
                       "detail": detail},
        }))
        return str(path)

    knobs = "pool=8;shrink_step=6;stages=spec_off,max_new:8;surge=8:60"
    paths = [
        rec(1, 100.0, coloc=knobs),
        rec(2, 40.0, coloc=knobs.replace("pool=8", "pool=4")),  # re-shaped
        rec(3, 39.0, coloc=knobs.replace("pool=8", "pool=4")),  # fine
        rec(4, 10.0, coloc=knobs.replace("pool=8", "pool=4")),  # REAL drop
    ]
    out = analyze(paths, threshold=0.10)
    rows = {r["round"]: r for r in out["rows"]}
    assert rows[2]["skip"].startswith("coloc_change:")
    assert rows[3]["skip"] is None and rows[3]["delta_pct"] is not None
    assert len(out["regressions"]) == 1
    assert out["regressions"][0]["to_round"] == 4
    # non-colocated records normalize together and stay comparable
    out2 = analyze([rec(5, 100.0), rec(6, 99.0)], threshold=0.10)
    assert out2["ok"]


# ---------------------------------------------------------------------------
# Heavy: the combined fault+chaos storm drill (make coloc-bench)
# ---------------------------------------------------------------------------

def test_coloc_bench_combined_storm_drill(tmp_path):
    """Run the real drill end to end on the CPU tier: every gate in the
    emitted record must hold (registered in tests/heavy_tests.txt)."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "OBS_DIR": str(tmp_path / "run"),
    }
    env.pop("XLA_FLAGS", None)  # the bench forces its own device count
    res = subprocess.run(
        [sys.executable, "scripts/coloc_bench.py"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=840,
        env=env,
    )
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    rec = json.loads(res.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "lm_coloc_tokens_per_sec"
    assert rec["value"] > 0
    gates = rec["detail"]["gates"]
    assert all(v is not False for v in gates.values()), gates
    actions = [
        d["action"] for d in rec["detail"]["storm"]["arbiter_decisions"]
    ]
    assert "shrink" in actions and "grow" in actions
    assert actions.index("shrink") < actions.index("grow")
    # the pool-ownership timeline renders from the captured events
    rep = subprocess.run(
        [sys.executable, "scripts/obs_report.py", str(tmp_path / "run")],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
        env=env,
    )
    assert rep.returncode == 0, rep.stderr
    assert "pool ownership" in rep.stdout
    assert "arbiter.shrink" in rep.stdout
