"""ViT family: registry reachability + real train steps on the 8-device mesh.

BASELINE.json names ViT-B/16 as a required config; these tests drive the
tiny variant through the same compiled DP step the pod uses, with
dropout>0 so the rng threading (train_step rngs={'dropout': ...}) is
actually exercised.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models import available_models, get_model
from distributeddeeplearning_tpu.models.vit import ViT
from distributeddeeplearning_tpu.training import (
    create_train_state,
    make_eval_step,
    make_train_step,
)
from distributeddeeplearning_tpu.training.train_step import replicate_state

CFG = TrainConfig(
    model="vit_ti16",
    num_classes=10,
    image_size=16,
    batch_size_per_device=2,
    weight_decay=0.0,
    compute_dtype="float32",
)


def _model(dropout=0.1):
    # 16x16 image / 16 patch -> 1 patch + cls token: smallest legal ViT.
    return ViT(
        variant="ti",
        patch_size=16,
        num_classes=10,
        dtype=jnp.float32,
        dropout=dropout,
    )


def _batch(global_batch=16, seed=0):
    rng = np.random.RandomState(seed)
    images = rng.randn(global_batch, 16, 16, 3).astype(np.float32)
    labels = rng.randint(0, 10, size=(global_batch,)).astype(np.int32)
    return images, labels


def test_registry_has_vit_family():
    names = available_models()
    for v in ("ti", "s", "b", "l", "h"):
        assert f"vit_{v}16" in names
    model = get_model("vit_b16", num_classes=10)
    assert isinstance(model, ViT)
    assert model.variant == "b" and model.patch_size == 16


def test_vit_b16_param_count():
    # Standard ViT-B/16 @224/1000 classes is ~86.6M params; count via
    # eval_shape so nothing is materialised.
    model = get_model("vit_b16")
    shapes = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 224, 224, 3), jnp.float32), train=False),
        jax.random.PRNGKey(0),
    )
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(shapes["params"]))
    assert 85e6 < n < 88e6, n


def test_vit_train_step_with_dropout(mesh8):
    """The regression VERDICT flagged: stochastic model through the DP step."""
    model = _model(dropout=0.1)
    tx = optax.sgd(0.05)
    state = replicate_state(
        create_train_state(model, CFG, tx, input_shape=(1, 16, 16, 3)), mesh8
    )
    step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    batch = shard_batch(_batch(), mesh8)
    state, metrics = step(state, batch)
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))


def test_vit_loss_decreases(mesh8):
    # Dropout on during training; measure progress with the deterministic
    # eval step so dropout noise can't flake the assertion.
    model = _model(dropout=0.1)
    tx = optax.sgd(0.05)
    state = replicate_state(
        create_train_state(model, CFG, tx, input_shape=(1, 16, 16, 3)), mesh8
    )
    step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    eval_step = make_eval_step(model, mesh8)
    batch = shard_batch(_batch(), mesh8)
    loss_before = float(eval_step(state, batch)["loss"])
    for _ in range(8):
        state, _ = step(state, batch)
    loss_after = float(eval_step(state, batch)["loss"])
    assert loss_after < loss_before, (loss_before, loss_after)


def test_vit_dropout_rng_varies_by_step(mesh8):
    """Same state+batch twice -> identical metrics (rng is a pure function
    of (seed, step, device)); consecutive steps -> different dropout masks,
    observable as different losses on the same fixed batch."""
    model = _model(dropout=0.5)
    tx = optax.sgd(0.0)  # lr 0: params never change, only step count
    state = replicate_state(
        create_train_state(model, CFG, tx, input_shape=(1, 16, 16, 3)), mesh8
    )
    step = make_train_step(model, tx, mesh8, CFG, donate_state=False)
    batch = shard_batch(_batch(), mesh8)
    s1, m1 = step(state, batch)
    _, m1b = step(state, batch)
    assert float(m1["loss"]) == float(m1b["loss"])  # deterministic replay
    _, m2 = step(s1, batch)
    assert float(m1["loss"]) != float(m2["loss"])  # new mask at new step


def test_vit_weight_decay_applies(mesh8):
    """Regression: logically-partitioned (boxed) params must still be seen
    by l2_kernel_penalty — params are unboxed in create_train_state."""
    model = _model(dropout=0.0)
    tx = optax.sgd(0.0)
    cfg_wd = CFG.replace(weight_decay=1e-2)
    state = create_train_state(model, CFG, tx, input_shape=(1, 16, 16, 3))
    batch = shard_batch(_batch(), mesh8)
    s_wd = replicate_state(state, mesh8)
    s_nw = replicate_state(state, mesh8)
    _, m_wd = make_train_step(model, tx, mesh8, cfg_wd, donate_state=False)(
        s_wd, batch
    )
    _, m_nw = make_train_step(model, tx, mesh8, CFG, donate_state=False)(s_nw, batch)
    assert float(m_wd["loss"]) > float(m_nw["loss"])


def test_vit_rejects_indivisible_image():
    with pytest.raises(ValueError):
        jax.eval_shape(
            lambda r: _model().init(
                r, jnp.zeros((1, 17, 17, 3), jnp.float32), train=False
            ),
            jax.random.PRNGKey(0),
        )


def test_vit_pallas_attention_matches_xla(mesh8):
    """The native tier reached from a real model: ViT with
    attn_impl='pallas' (flash kernel, interpreter mode on CPU) produces
    the same logits as the XLA einsum path and trains a step."""
    img = np.random.RandomState(0).randn(16, 32, 32, 3).astype(np.float32)
    lbl = np.random.RandomState(1).randint(0, 10, size=(16,)).astype(np.int32)

    def build(impl):
        m = ViT(
            variant="ti", patch_size=8, num_classes=10,
            dtype=jnp.float32, attn_impl=impl, dropout=0.0,
        )
        return m

    m_xla, m_fl = build("xla"), build("pallas")
    tx = optax.sgd(0.05)
    state = create_train_state(m_xla, CFG, tx, input_shape=(1, 32, 32, 3))
    logits_xla = m_xla.apply(
        {"params": state.params, "batch_stats": {}}, img, train=False
    )
    logits_fl = m_fl.apply(
        {"params": state.params, "batch_stats": {}}, img, train=False
    )
    np.testing.assert_allclose(
        np.asarray(logits_fl), np.asarray(logits_xla), atol=2e-4
    )
    # and the DP step runs through the kernel. check_vma=False only
    # because the Pallas HLO *interpreter* (CPU mesh) trips the checker;
    # the compiled TPU path runs with checking on (verified on a v5e).
    state = replicate_state(state, mesh8)
    step = make_train_step(m_fl, tx, mesh8, CFG, donate_state=False, check_vma=False)
    new_state, metrics = step(state, shard_batch((img, lbl), mesh8))
    assert np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1


def test_get_model_attn_impl_plumbing():
    m = get_model("vit_ti16", num_classes=10, attn_impl="pallas")
    assert m.attn_impl == "pallas"
    # conv models ignore the knob instead of crashing
    r = get_model("resnet18", num_classes=10, attn_impl="pallas")
    assert r.depth == 18


def test_vit_fused_packed_attention_matches_xla(mesh8):
    """attn_impl='fused' (packed small-T kernel, interpreter mode on CPU)
    equals the XLA einsum path from the same params — the path the TPU
    'auto' default takes for ViT shapes (PROFILE.md round-4) — and trains
    a DP step. variant='s' because the packed kernel needs whole
    128-lane head groups (6 heads × d=64; 'ti' has 3 heads)."""
    img = np.random.RandomState(0).randn(16, 32, 32, 3).astype(np.float32)
    lbl = np.random.RandomState(1).randint(0, 10, size=(16,)).astype(np.int32)

    def build(impl):
        return ViT(
            variant="s", patch_size=8, num_classes=10,
            dtype=jnp.float32, attn_impl=impl, dropout=0.0,
        )

    m_xla, m_fused = build("xla"), build("fused")
    tx = optax.sgd(0.05)
    state = create_train_state(m_xla, CFG, tx, input_shape=(1, 32, 32, 3))
    logits_xla = m_xla.apply(
        {"params": state.params, "batch_stats": {}}, img, train=False
    )
    logits_fused = m_fused.apply(
        {"params": state.params, "batch_stats": {}}, img, train=False
    )
    np.testing.assert_allclose(
        np.asarray(logits_fused), np.asarray(logits_xla), atol=2e-4
    )
    state = replicate_state(state, mesh8)
    # default check_vma: _pallas_interpreted covers impl='fused' off-TPU
    step = make_train_step(m_fused, tx, mesh8, CFG, donate_state=False)
    new_state, metrics = step(state, shard_batch((img, lbl), mesh8))
    assert np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1


def test_vit_auto_impl_resolves_to_xla_off_tpu():
    """The 'auto' default must not select the Pallas kernel on non-TPU
    backends: logits equal the explicit-xla build bit-for-bit."""
    img = np.random.RandomState(0).randn(4, 32, 32, 3).astype(np.float32)
    m_auto = ViT(variant="s", patch_size=8, num_classes=10,
                 dtype=jnp.float32, attn_impl="auto", dropout=0.0)
    m_xla = ViT(variant="s", patch_size=8, num_classes=10,
                dtype=jnp.float32, attn_impl="xla", dropout=0.0)
    variables = m_xla.init(jax.random.PRNGKey(0), img[:1], train=False)
    a = m_auto.apply(variables, img, train=False)
    b = m_xla.apply(variables, img, train=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
