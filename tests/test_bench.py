"""bench.py smoke: the driver's benchmark harness must stay runnable."""

import numpy as np


def test_run_bench_smoke(mesh8):
    # knobs are explicit parameters now (main() owns the env parsing)
    import bench

    ips, n_dev = bench.run_bench(2, devices=2, depth=18, image_size=16)
    assert n_dev == 2
    assert np.isfinite(ips) and ips > 0


def test_run_bench_named_model_smoke(mesh8):
    import bench

    ips, n_dev = bench.run_bench(
        2, devices=2, model_name="vit_ti16", image_size=16
    )
    assert n_dev == 2
    assert np.isfinite(ips) and ips > 0


def test_bench_scaling_emits_efficiency(mesh8, capsys, monkeypatch):
    """BENCH_SCALING=1 must produce the scaling_efficiency field on the
    multi-device mesh — the 8→64 measurement path cannot rot before
    multi-chip hardware arrives (BASELINE >90% target)."""
    import json

    import bench

    monkeypatch.setenv("BENCH_SCALING", "1")
    monkeypatch.setenv("BENCH_BATCH", "2")
    monkeypatch.setenv("BENCH_DEPTH", "18")
    monkeypatch.setenv("BENCH_IMAGE_SIZE", "16")
    assert bench.main() == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    detail = out["detail"]
    assert "scaling_efficiency" in detail, detail
    assert 0.0 < detail["scaling_efficiency"] <= 1.5
    assert detail["images_per_sec_1_device"] > 0


def test_bench_decode_mode(mesh8, capsys, monkeypatch):
    """BENCH_DECODE=1 emits the decode-throughput JSON line."""
    import json

    import bench

    monkeypatch.setenv("BENCH_DECODE", "1")
    monkeypatch.setenv("BENCH_MODEL", "lm_tiny")
    monkeypatch.setenv("BENCH_VOCAB", "64")
    monkeypatch.setenv("BENCH_BATCH", "2")
    monkeypatch.setenv("BENCH_PROMPT_LEN", "4")
    monkeypatch.setenv("BENCH_NEW_TOKENS", "4")
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "lm_tiny_decode_tokens_per_sec"
    assert out["value"] > 0
