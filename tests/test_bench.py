"""bench.py smoke: the driver's benchmark harness must stay runnable."""

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _cpu_platform_env(monkeypatch):
    """bench's device-init guard probes the backend in SUBPROCESSES
    (round 5) — the conftest's in-process jax.config forcing doesn't
    reach them, so without this env the probes would touch the axon
    relay (and hang to their timeout when it's down)."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")


def test_run_bench_smoke(mesh8):
    # knobs are explicit parameters now (main() owns the env parsing)
    import bench

    ips, n_dev, perf = bench.run_bench(2, devices=2, depth=18, image_size=16)
    assert n_dev == 2
    assert np.isfinite(ips) and ips > 0
    # sync-free accounting: compile time measured apart from the loop,
    # and the measured region syncs exactly once (the closing fence).
    assert perf["compile_sec"] > 0
    assert perf["host_sync_count"] == 1


def test_run_bench_named_model_smoke(mesh8):
    import bench

    ips, n_dev, perf = bench.run_bench(
        2, devices=2, model_name="vit_ti16", image_size=16
    )
    assert n_dev == 2
    assert np.isfinite(ips) and ips > 0
    assert perf["host_sync_count"] == 1


def test_bench_scaling_emits_efficiency(mesh8, capsys, monkeypatch):
    """BENCH_SCALING=1 must produce the scaling_efficiency field on the
    multi-device mesh — the 8→64 measurement path cannot rot before
    multi-chip hardware arrives (BASELINE >90% target)."""
    import json

    import bench

    monkeypatch.setenv("BENCH_SCALING", "1")
    monkeypatch.setenv("BENCH_BATCH", "2")
    monkeypatch.setenv("BENCH_DEPTH", "18")
    monkeypatch.setenv("BENCH_IMAGE_SIZE", "16")
    assert bench.main() == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    detail = out["detail"]
    assert "scaling_efficiency" in detail, detail
    assert 0.0 < detail["scaling_efficiency"] <= 1.5
    assert detail["images_per_sec_1_device"] > 0
    # perf-trajectory fields ride every bench line (ISSUE 1)
    assert out["compile_sec"] > 0
    assert out["host_sync_count"] >= 1


def test_bench_decode_mode(mesh8, capsys, monkeypatch):
    """BENCH_DECODE=1 emits the decode-throughput JSON line."""
    import json

    import bench

    monkeypatch.setenv("BENCH_DECODE", "1")
    monkeypatch.setenv("BENCH_MODEL", "lm_tiny")
    monkeypatch.setenv("BENCH_VOCAB", "64")
    monkeypatch.setenv("BENCH_BATCH", "2")
    monkeypatch.setenv("BENCH_PROMPT_LEN", "4")
    monkeypatch.setenv("BENCH_NEW_TOKENS", "4")
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "lm_tiny_decode_tokens_per_sec"
    assert out["value"] > 0


def test_recertify_run_protocol_tolerates_partial_json(monkeypatch):
    """ADVICE r5: a killed child can leave a partial '{'-prefixed stdout
    line; the battery must record a failed row, not abort on
    JSONDecodeError. Also: children inherit a default persistent
    compilation cache dir (opt out with COMPILATION_CACHE_DIR=\"\")."""
    import subprocess
    import types

    from scripts import recertify

    seen_env = {}

    def fake_run(cmd, env=None, timeout=None, capture_output=None, text=None):
        seen_env.update(env or {})
        return types.SimpleNamespace(
            stdout='garbage\n{"metric": "x", "value": 3.0, truncated',
            stderr="", returncode=1,
        )

    monkeypatch.setattr(subprocess, "run", fake_run)
    rec = recertify.run_protocol("resnet50", {"BENCH_BATCH": "1"}, 5.0)
    assert "unparseable JSON" in rec["error"]
    assert seen_env["COMPILATION_CACHE_DIR"].endswith(".jax_cache")

    monkeypatch.setenv("COMPILATION_CACHE_DIR", "")  # explicit opt-out
    recertify.run_protocol("resnet50", {"BENCH_BATCH": "1"}, 5.0)
    assert seen_env["COMPILATION_CACHE_DIR"] == ""


def test_recertify_serve_row_dispatches_to_serve_bench(monkeypatch):
    """The serve_lm protocol runs scripts/serve_bench.py (its own
    entrypoint, not a bench.py mode) and ambient SERVE_* protocol vars
    are scrubbed before the row's own env applies."""
    import subprocess
    import types

    from scripts import recertify

    seen = {}

    def fake_run(cmd, env=None, timeout=None, capture_output=None, text=None):
        seen["cmd"] = cmd
        seen["env"] = dict(env or {})
        return types.SimpleNamespace(
            stdout='{"metric": "serve_continuous_tokens_per_sec", '
                   '"value": 5.0}',
            stderr="", returncode=0,
        )

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setenv("SERVE_SLOTS", "99")  # ambient leak attempt
    rec = recertify.run_protocol(
        "serve_lm", recertify.PROTOCOLS["serve_lm"], 5.0
    )
    assert rec["value"] == 5.0
    assert seen["cmd"][-1].endswith("scripts/serve_bench.py")
    assert seen["env"]["SERVE_SLOTS"] == "8"  # the row's value, not 99
    assert "_script" not in seen["env"]
    assert recertify.PROTOCOLS["serve_lm"]["_script"]  # source not mutated

    # every other row still runs bench.py
    recertify.run_protocol("resnet50", {"BENCH_BATCH": "1"}, 5.0)
    assert seen["cmd"][-1].endswith("bench.py")


def test_device_init_cpu_tier_fallback(monkeypatch, capsys):
    """Exhausted TPU probes now fall back to an explicit tier=cpu run
    (BENCH_r04/r05: the relay outage used to emit value 0.0, which the
    trajectory read as a 100% regression instead of an infra outage).
    The fallback probes CPU init first and tags every record with tier +
    the outage diagnosis; BENCH_CPU_FALLBACK=0 restores the hard fail,
    whose record now carries tier=outage."""
    import json

    import bench

    monkeypatch.setattr(bench, "_TIER_NOTE", None)

    def fake_probe(timeout_s):
        import os

        # TPU probe (no/any platform) hangs; the cpu fallback probe works
        return "ok" if os.environ.get("JAX_PLATFORMS") == "cpu" else "timeout"

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("BENCH_MODEL", "lm_small")
    monkeypatch.setattr(bench, "_probe_device_init", fake_probe)
    bench._guard_device_init(attempts=2, probe_timeout_s=1.0, backoff_s=0.01)
    assert bench._TIER_NOTE is not None
    assert bench._TIER_NOTE["tier"] == "cpu"
    assert "relay down" in bench._TIER_NOTE["tpu_outage"]
    # every record emitted from here on carries the tier marker
    capsys.readouterr()
    bench._emit_record({"metric": "m", "value": 1.0, "unit": "u",
                        "vs_baseline": 0.0})
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 1.0 and rec["tier"] == "cpu"
    assert "tpu_outage" in rec

    # BENCH_CPU_FALLBACK=0 opts out: the guard hard-fails with the
    # structured record, now tier-tagged as an outage
    import os as _os

    monkeypatch.setattr(bench, "_TIER_NOTE", None)
    monkeypatch.setenv("BENCH_CPU_FALLBACK", "0")
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)  # guard re-set it
    monkeypatch.setattr(_os, "_exit", lambda rc: (_ for _ in ()).throw(
        SystemExit(rc)
    ))
    capsys.readouterr()
    with pytest.raises(SystemExit):
        bench._guard_device_init(
            attempts=1, probe_timeout_s=1.0, backoff_s=0.01
        )
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 0.0 and rec["tier"] == "outage"
    assert "device init" in rec["error"]


def test_device_init_watchdog():
    """A dead accelerator relay makes jax.devices() hang forever
    (observed: the tunnel went down and every jax call blocked). The
    bench must fail within its bounded retry budget with a structured
    record naming the protocol that was asked for, not hang the driver.
    Subprocess child (fresh interpreter — fork-after-threads from a
    JAX-initialized pytest process can deadlock on inherited locks)."""
    import json
    import os
    import subprocess
    import sys

    import bench

    # normal path: probe succeeds (cpu env from the autouse fixture),
    # in-process init is already cpu — no-op
    bench._guard_device_init(attempts=1, probe_timeout_s=60.0)
    # env resolves the failure record's metric before any jax call
    assert bench._intended_metric()[0].startswith("resnet50_synthetic")

    # Failure path: in-process device_count mocked to hang. Two ways the
    # guard can conclude, both asserted by the record's text: the probe
    # grandchildren time out (relay down / probe window too small), or a
    # probe succeeds and the in-process watchdog fires on the mocked
    # hang. Either way: rc 1, value 0.0, the asked-for protocol's metric.
    child = (
        "import time, unittest.mock as mock\n"
        "import bench\n"
        "with mock.patch.object(bench.jax, 'device_count',"
        " side_effect=lambda: time.sleep(30)):\n"
        "    bench._guard_device_init()\n"
    )
    env = {
        **os.environ,
        "BENCH_MODEL": "lm_small",
        "BENCH_INIT_PROBES": "2",
        "BENCH_INIT_TIMEOUT": "2",
        "BENCH_INIT_BACKOFF": "0.1",
    }
    env.pop("JAX_PLATFORMS", None)  # probe the default (hangable) backend
    r = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True, text=True, timeout=120,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 1, (r.stdout, r.stderr)
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 0.0 and "device init" in rec["error"]
    assert rec["metric"] == "lm_small_synthetic_train_tokens_per_sec"
    assert rec["unit"] == "tokens/sec"
