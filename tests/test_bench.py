"""bench.py smoke: the driver's benchmark harness must stay runnable."""

import numpy as np


def test_run_bench_smoke(mesh8):
    # knobs are explicit parameters now (main() owns the env parsing)
    import bench

    ips, n_dev = bench.run_bench(2, devices=2, depth=18, image_size=16)
    assert n_dev == 2
    assert np.isfinite(ips) and ips > 0


def test_run_bench_named_model_smoke(mesh8):
    import bench

    ips, n_dev = bench.run_bench(
        2, devices=2, model_name="vit_ti16", image_size=16
    )
    assert n_dev == 2
    assert np.isfinite(ips) and ips > 0
