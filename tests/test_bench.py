"""bench.py smoke: the driver's benchmark harness must stay runnable."""

import numpy as np


def test_run_bench_smoke(monkeypatch, mesh8):
    monkeypatch.setenv("BENCH_DEPTH", "18")
    monkeypatch.setenv("BENCH_IMAGE_SIZE", "16")
    import bench

    ips, n_dev = bench.run_bench(2, devices=2)
    assert n_dev == 2
    assert np.isfinite(ips) and ips > 0
