import numpy as np

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.training.schedules import create_lr_schedule


SPE = 10  # steps per epoch


def _sched(**kw):
    cfg = TrainConfig(**kw)
    return create_lr_schedule(cfg, SPE, world_size=8)


def test_peak_is_scaled_by_world_size():
    s = _sched()
    # after warmup, before first decay epoch
    assert np.isclose(float(s(10 * SPE)), 0.001 * 8)


def test_warmup_ramps_from_single_device_lr():
    s = _sched()
    assert np.isclose(float(s(0)), 0.001)
    assert float(s(2 * SPE)) < float(s(4 * SPE)) < 0.008 + 1e-9


def test_decay_fires_at_documented_epochs():
    # Regression: join_schedules offsets the inner schedule by
    # warmup_steps, which un-corrected fired decay at 35/65/85.
    s = _sched()
    peak = 0.008
    assert np.isclose(float(s(30 * SPE - 1)), peak)
    assert np.isclose(float(s(30 * SPE)), peak * 0.1)
    assert np.isclose(float(s(60 * SPE - 1)), peak * 0.1)
    assert np.isclose(float(s(60 * SPE)), peak * 0.01)
    assert np.isclose(float(s(80 * SPE)), peak * 0.001)


def test_no_warmup():
    s = _sched(warmup_epochs=0)
    assert np.isclose(float(s(0)), 0.008)
    assert np.isclose(float(s(30 * SPE)), 0.0008)


def test_unscaled_lr():
    s = _sched(scale_lr_by_world_size=False)
    assert np.isclose(float(s(10 * SPE)), 0.001)


def test_decay_epoch_inside_warmup_is_dropped():
    # decay boundary before warmup end must not produce a negative key
    s = _sched(warmup_epochs=40, lr_decay_epochs=(30, 60))
    assert np.isclose(float(s(41 * SPE)), 0.008)  # 30-epoch decay dropped
    assert np.isclose(float(s(60 * SPE)), 0.0008)


def test_absolute_multiplier_factors():
    # Per-boundary factors: absolute multipliers 0.1 then 0.05 of base
    # require ratios (0.1, 0.5).
    cfg = TrainConfig(
        lr_decay_epochs=(30, 60), lr_decay_factors=(0.1, 0.5), warmup_epochs=0
    )
    s = create_lr_schedule(cfg, SPE, world_size=1)
    assert np.isclose(float(s(30 * SPE)), 0.001 * 0.1)
    assert np.isclose(float(s(60 * SPE)), 0.001 * 0.05)


def test_mismatched_factors_raise():
    import pytest

    cfg = TrainConfig(lr_decay_epochs=(30, 60), lr_decay_factors=(0.1,))
    with pytest.raises(ValueError, match="lr_decay_factors"):
        create_lr_schedule(cfg, SPE, world_size=1)
