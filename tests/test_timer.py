import time

from distributeddeeplearning_tpu.utils.timer import Timer, timer


def test_timer_context_manager():
    with Timer() as t:
        time.sleep(0.01)
    assert 0.005 < t.elapsed < 1.0


def test_timer_output_sink():
    out = []
    with Timer(output=out.append, fmt="{:.1f}"):
        pass
    assert len(out) == 1


def test_timer_accumulates():
    t = Timer()
    t.start()
    t.stop()
    first = t.elapsed
    t.start()
    time.sleep(0.01)
    t.stop()
    assert t.elapsed > first


def test_timer_reset():
    t = Timer()
    t.start()
    t.stop()
    t.reset()
    assert t.elapsed == 0.0


def test_timer_decorator():
    out = []

    @timer(output=out.append)
    def add(a, b):
        return a + b

    assert add(2, 3) == 5
    assert len(out) == 1 and "add" in out[0]
