"""Optimizer/schedule tier tests: adamw, cosine/constant schedules,
gradient accumulation — all through the same engines as SGD."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributeddeeplearning_tpu.config import TrainConfig
from distributeddeeplearning_tpu.data.pipeline import shard_batch
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.training import create_train_state, make_train_step
from distributeddeeplearning_tpu.training.optimizer import create_optimizer
from distributeddeeplearning_tpu.training.schedules import create_lr_schedule
from distributeddeeplearning_tpu.training.train_step import replicate_state

VOCAB, T = 32, 8


def _lm():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=T, dtype=jnp.float32
    )


def _rows(n, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, VOCAB, size=(n, T + 1)).astype(np.int32)


def test_env_wiring():
    cfg = TrainConfig.from_env(
        {
            "OPTIMIZER": "adamw",
            "LR_SCHEDULE": "cosine",
            "GRAD_ACCUM_STEPS": "4",
            "WEIGHT_DECAY": "0",
            "DECOUPLED_WEIGHT_DECAY": "0.1",
        }
    )
    assert cfg.optimizer == "adamw"
    assert cfg.lr_schedule == "cosine"
    assert cfg.grad_accum_steps == 4
    assert cfg.weight_decay == 0.0
    assert cfg.decoupled_weight_decay == 0.1


def test_unknown_optimizer_and_schedule_rejected():
    with pytest.raises(ValueError, match="unknown optimizer"):
        create_optimizer(TrainConfig(optimizer="lamb"), 10)
    with pytest.raises(ValueError, match="unknown lr_schedule"):
        create_lr_schedule(TrainConfig(lr_schedule="poly"), 10)


def test_cosine_schedule_shape():
    cfg = TrainConfig(
        lr_schedule="cosine", base_lr=0.1, warmup_epochs=1, epochs=10,
        scale_lr_by_world_size=False,
    )
    # world_size=8: warmup starts from the single-device LR peak/8
    sched = create_lr_schedule(cfg, steps_per_epoch=100, world_size=8)
    peak = max(float(sched(s)) for s in range(0, 1000, 10))
    assert np.isclose(peak, 0.1, rtol=0.05)
    assert float(sched(0)) < 0.05  # warming up from peak/8
    assert float(sched(999)) < 0.01 * 0.1  # decayed to ~0
    # constant: warm then flat
    cfg2 = cfg.replace(lr_schedule="constant")
    sched2 = create_lr_schedule(cfg2, steps_per_epoch=100, world_size=8)
    assert np.isclose(float(sched2(100)), 0.1)
    assert np.isclose(float(sched2(999)), 0.1)


def test_adamw_cosine_trains(mesh8):
    cfg = TrainConfig(
        optimizer="adamw", lr_schedule="cosine", base_lr=1e-3,
        warmup_epochs=0, epochs=2, num_classes=VOCAB, weight_decay=0.0,
        decoupled_weight_decay=0.01, batch_size_per_device=2,
        compute_dtype="float32",
    )
    model = _lm()
    tx, sched = create_optimizer(cfg, steps_per_epoch=8, world_size=8)
    state = replicate_state(
        create_train_state(model, cfg, tx, input_shape=(1, T),
                           input_dtype=jnp.int32),
        mesh8,
    )
    step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
    rows = _rows(16)
    batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh8)
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_grad_accumulation_equals_big_batch(mesh8):
    """k accumulation micro-steps == one step on the k×-sized batch
    (MultiSteps averages gradients; LM has no BN, dropout off)."""
    model = _lm()
    rows = _rows(32, seed=3)
    halves = [rows[:16], rows[16:]]

    def run(cfg, batches):
        tx, _ = create_optimizer(cfg, steps_per_epoch=4, world_size=8)
        state = replicate_state(
            create_train_state(model, cfg, tx, input_shape=(1, T),
                               input_dtype=jnp.int32),
            mesh8,
        )
        step = make_train_step(model, tx, mesh8, cfg, donate_state=False)
        for b in batches:
            state, _ = step(state, shard_batch((b[:, :-1], b[:, 1:]), mesh8))
        return jax.device_get(state.params)

    base = TrainConfig(
        num_classes=VOCAB, weight_decay=0.0, warmup_epochs=0,
        scale_lr_by_world_size=False, base_lr=0.1, momentum=0.0,
        compute_dtype="float32",
    )
    accum = run(base.replace(grad_accum_steps=2), halves)
    big = run(base, [rows])
    for a, b in zip(jax.tree.leaves(accum), jax.tree.leaves(big)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_accum_under_pjit_engine(devices):
    """MultiSteps state passes through the GSPMD engine (sharded
    opt-state constraint handles the wrapped structure)."""
    from distributeddeeplearning_tpu.models.sharding import LOGICAL_RULES
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.pjit_step import (
        create_sharded_train_state,
        make_pjit_train_step,
    )

    mesh = create_mesh(axes=("data", "model"), shape=(2, 4))
    cfg = TrainConfig(
        num_classes=VOCAB, weight_decay=0.0, grad_accum_steps=2,
        optimizer="adamw", compute_dtype="float32",
    )
    model = _lm()
    tx, _ = create_optimizer(cfg, steps_per_epoch=4, world_size=8)
    state = create_sharded_train_state(
        model, cfg, tx, mesh, LOGICAL_RULES,
        input_shape=(1, T), input_dtype=jnp.int32,
    )
    step = make_pjit_train_step(model, tx, mesh, cfg, donate_state=False)
    rows = _rows(4, seed=5)
    with mesh:
        batch = shard_batch((rows[:, :-1], rows[:, 1:]), mesh)
        for _ in range(4):
            state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(jax.device_get(state.step)) == 4
