"""Live telemetry plane oracles (ISSUE 7).

The plane's claims, each pinned here:

* **Tailer** (`obs/tail.py`) — incremental, exactly-once delivery
  across polls; a partial final line is never emitted torn and never
  twice; files appearing mid-run (restart suffixes
  ``events-p0-r1.jsonl``) join seamlessly; events from two fake hosts
  with unrelated monotonic clocks land on ONE wall timeline via their
  meta clock pairs; truncation resets the cursor.
* **Rollup** (`obs/rollup.py`) — windowed rates/gauges/quantiles from
  bounded state; the log-histogram quantiles stay within the documented
  error bound of *exact* percentiles; ``rollup.json`` is published
  atomically and a torn read degrades to None.
* **SLO engine** (`obs/slo.py`) — the ``SLO_SPEC`` grammar
  (round-tripping the docstring examples, rejecting junk), multi-window
  burn-rate semantics (short AND long to breach, short alone to
  recover), ``finite`` objectives, breach/recover points on the bus.
* **Feedback** (`serving/scheduler.py`) — AdaptiveAdmissionPolicy
  derates ``prefills_per_step`` + the QueueFull threshold from a
  burning-latency snapshot and restores on recovery, deterministically;
  the END-TO-END oracle runs a real SlotEngine server under an
  injected-breach SLO with the plane live and asserts the
  shed-then-recover sequence from the MERGED event stream:
  ``slo_breach`` → ``serve.admission_derate`` (lowered gauge) →
  ``slo_recover`` → ``serve.admission_restore``.
* **Satellites** — the bus's ``OBS_FLUSH_EVERY_S`` bounded-staleness
  flush, the launcher watchdog's telemetry liveness signature,
  ``scripts/obs_watch.py --once``, ``scripts/bench_trend.py`` tier
  skipping, and the post-hoc report's SLO section.
"""

import json
import math
import os
import time
import types

import numpy as np
import pytest

from distributeddeeplearning_tpu import obs
from distributeddeeplearning_tpu.obs import report as obs_report
from distributeddeeplearning_tpu.obs.bus import EventBus
from distributeddeeplearning_tpu.obs.rollup import (
    HIST_GROWTH,
    LivePlane,
    WindowedAggregator,
    read_snapshot,
    write_snapshot,
)
from distributeddeeplearning_tpu.obs.slo import (
    BURN_MAX,
    SloEngine,
    parse_objective,
    parse_slo_spec,
)
from distributeddeeplearning_tpu.obs.tail import Tailer, activity_signature

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_bus():
    obs.reset()
    yield
    obs.reset()


def _write(path, *records, mode="a"):
    with open(path, mode) as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")


def _meta(p, mono0, wall0):
    return {"kind": "meta", "schema": 1, "run": "r-t", "p": p,
            "mono0": mono0, "wall0": wall0}


# ---------------------------------------------------------------------------
# Tailer
# ---------------------------------------------------------------------------

def test_tailer_incremental_exactly_once_with_partial_line(tmp_path):
    p0 = tmp_path / "events-p0.jsonl"
    _write(p0, _meta(0, 100.0, 1000.0),
           {"t": 101.0, "kind": "point", "name": "a", "p": 0}, mode="w")
    t = Tailer(str(tmp_path))
    assert [e["name"] for e in t.poll()] == ["a"]
    assert t.poll() == []  # nothing new, nothing re-delivered
    # A torn tail (writer flushed mid-record) must be held back whole...
    with open(p0, "a") as fh:
        fh.write('{"t": 102.0, "kind": "point", "name": "b"')
    assert t.poll() == []
    # ...and delivered exactly once when completed.
    with open(p0, "a") as fh:
        fh.write(', "p": 0}\n')
    ev = t.poll()
    assert [e["name"] for e in ev] == ["b"]
    assert t.errors == 0
    assert t.events_seen == 2


def test_tailer_discovers_restart_suffix_files_mid_run(tmp_path):
    p0 = tmp_path / "events-p0.jsonl"
    _write(p0, _meta(0, 100.0, 1000.0),
           {"t": 101.0, "kind": "point", "name": "a", "p": 0}, mode="w")
    t = Tailer(str(tmp_path))
    assert len(t.poll()) == 1
    # A restart attempt's file appears later (OBS_PROC_SUFFIX identity).
    _write(tmp_path / "events-p0-r1.jsonl", _meta("p0-r1", 5.0, 2000.0),
           {"t": 6.0, "kind": "point", "name": "after-restart",
            "p": "p0-r1"}, mode="w")
    ev = t.poll()
    assert [e["name"] for e in ev] == ["after-restart"]
    assert ev[0]["wall"] == pytest.approx(2001.0)
    assert len(t.files) == 2


def test_tailer_aligns_two_fake_hosts_on_one_wall_timeline(tmp_path):
    # Host A's monotonic clock started ~eons before host B's; wall order
    # is the OPPOSITE of file order. Only the meta clock pairs can sort
    # this correctly.
    _write(tmp_path / "events-pA.jsonl", _meta("A", 50000.0, 1000.0),
           {"t": 50003.0, "kind": "point", "name": "late-on-A", "p": "A"},
           mode="w")
    _write(tmp_path / "events-pB.jsonl", _meta("B", 7.0, 1000.0),
           {"t": 8.0, "kind": "point", "name": "early-on-B", "p": "B"},
           mode="w")
    ev = Tailer(str(tmp_path)).poll()
    assert [e["name"] for e in ev] == ["early-on-B", "late-on-A"]
    assert ev[0]["wall"] == pytest.approx(1001.0)
    assert ev[1]["wall"] == pytest.approx(1003.0)


def test_tailer_resets_on_truncation_and_skips_merged_file(tmp_path):
    p0 = tmp_path / "events-p0.jsonl"
    _write(p0, _meta(0, 100.0, 1000.0),
           {"t": 101.0, "kind": "point", "name": "old", "p": 0}, mode="w")
    # the launcher's merged file must never be tailed (it duplicates
    # every part file)
    _write(tmp_path / "events.jsonl", _meta(0, 100.0, 1000.0),
           {"t": 101.0, "kind": "point", "name": "dup", "p": 0}, mode="w")
    t = Tailer(str(tmp_path))
    assert [e["name"] for e in t.poll()] == ["old"]
    # rewrite smaller (a restart WITHOUT the suffix identity)
    _write(p0, _meta(0, 1.0, 3000.0),
           {"t": 2.0, "kind": "point", "name": "new", "p": 0}, mode="w")
    ev = t.poll()
    assert [e["name"] for e in ev] == ["new"]
    assert ev[0]["wall"] == pytest.approx(3001.0)  # NEW clock pair applies


def test_activity_signature_reflects_file_growth(tmp_path):
    p0 = tmp_path / "events-p0.jsonl"
    _write(p0, _meta(0, 1.0, 1.0), mode="w")
    s1 = activity_signature(str(tmp_path))
    s2 = activity_signature(str(tmp_path))
    assert s1 == s2
    _write(p0, {"t": 2.0, "kind": "point", "name": "x", "p": 0})
    assert activity_signature(str(tmp_path)) != s1


# ---------------------------------------------------------------------------
# Bus flush (OBS_FLUSH_EVERY_S satellite)
# ---------------------------------------------------------------------------

def _disk_names(path):
    return [json.loads(ln)["name"] for ln in open(path) if
            json.loads(ln).get("kind") != "meta"]


def test_bus_time_based_flush_bounds_staleness(tmp_path):
    bus = EventBus(directory=str(tmp_path), proc=0, flush_every_s=0.05)
    bus.point("first")
    assert _disk_names(bus.path) == []  # inside the staleness budget
    time.sleep(0.06)
    bus.point("second")  # first emit past the budget flushes the buffer
    assert _disk_names(bus.path) == ["first", "second"]


def test_bus_flush_every_zero_restores_epoch_boundary_behavior(tmp_path):
    bus = EventBus(directory=str(tmp_path), proc=0, flush_every_s=0.0)
    bus.point("a")
    time.sleep(0.02)
    bus.point("b")
    assert _disk_names(bus.path) == []  # only explicit flush (or size)
    bus.flush()
    assert _disk_names(bus.path) == ["a", "b"]


def test_bus_flush_knob_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("OBS_FLUSH_EVERY_S", "0.01")
    bus = EventBus(directory=str(tmp_path), proc=0)
    assert bus._flush_every_s == pytest.approx(0.01)
    monkeypatch.setenv("OBS_FLUSH_EVERY_S", "junk")
    assert EventBus(proc=1)._flush_every_s == 5.0  # default survives junk


# ---------------------------------------------------------------------------
# Rollup: windows, rates, quantile accuracy, atomic snapshot
# ---------------------------------------------------------------------------

def test_rollup_quantiles_within_bound_of_exact_percentiles():
    rng = np.random.RandomState(7)
    durs = rng.lognormal(mean=-5.0, sigma=1.2, size=4000)
    agg = WindowedAggregator(60.0, slice_s=1.0)
    for i, d in enumerate(durs):
        agg.add({"kind": "span", "name": "s", "dur": float(d),
                 "wall": 1000.0 + (i % 50)})
    # One histogram bucket is a HIST_GROWTH ratio; the geometric-midpoint
    # readback is off by at most sqrt(growth) either way (+ float slop).
    bound = HIST_GROWTH ** 0.5 * 1.01
    for q in (0.50, 0.95, 0.99):
        exact = float(np.percentile(durs, q * 100))
        est = agg.span_quantile("s", q)
        assert 1.0 / bound <= est / exact <= bound, (q, est, exact)


def test_rollup_windows_expire_and_memory_stays_bounded():
    agg = WindowedAggregator(10.0, slice_s=1.0)
    for sec in range(10_000):
        agg.add({"kind": "counter", "name": "c", "value": 2,
                 "wall": float(sec)})
        agg.add({"kind": "span", "name": "s", "dur": 0.01,
                 "wall": float(sec)})
    # bounded state: only the retained window's slices survive 10k s
    assert len(agg._slices) <= int(agg.retain_s / agg.slice_s) + 2
    assert agg.counter_sum("c") == pytest.approx(20.0)  # 10 slices x 2
    assert agg.counter_rate("c") == pytest.approx(2.0)
    # an explicitly narrower window
    assert agg.counter_sum("c", window_s=3.0) == pytest.approx(6.0)
    # events older than the window are gone from the quantile view
    assert sum(agg.span_hist("s").values()) == 10


def test_rollup_gauges_last_value_wins_with_age():
    agg = WindowedAggregator(60.0)
    agg.add({"kind": "gauge", "name": "g", "value": 1.0, "wall": 100.0})
    agg.add({"kind": "gauge", "name": "g", "value": 2.5, "wall": 120.0})
    assert agg.gauge_last("g") == 2.5
    snap = agg.snapshot(now=130.0)
    assert snap["gauges"]["g"] == {"value": 2.5, "age_s": 10.0}


def test_snapshot_atomic_write_and_torn_read(tmp_path):
    path = str(tmp_path / "rollup.json")
    snap = {"schema": 1, "counters": {"c": {"sum": 1.0}}}
    write_snapshot(path, snap)
    assert read_snapshot(path)["counters"]["c"]["sum"] == 1.0
    # no temp litter left behind by the atomic replace
    assert os.listdir(tmp_path) == ["rollup.json"]
    with open(path, "w") as fh:
        fh.write('{"torn": ')
    assert read_snapshot(path) is None  # degrade, never raise
    assert read_snapshot(str(tmp_path / "absent.json")) is None


# ---------------------------------------------------------------------------
# SLO grammar + burn-rate engine
# ---------------------------------------------------------------------------

def test_slo_grammar_docstring_examples():
    objs = parse_slo_spec(
        "serve.ttft:p99 < 250ms over 60s; epoch.loss finite\n"
        "serve.rejected:rate < 1% over 30s  # comment\n"
        "queue.depth:last <= 32"
    )
    o0, o1, o2, o3 = objs
    assert (o0.metric, o0.stat, o0.op) == ("serve.ttft", "p99", "<")
    assert o0.threshold == pytest.approx(0.25)  # ms normalized to s
    assert o0.window_s == 60.0
    assert (o1.metric, o1.stat) == ("epoch.loss", "finite")
    assert (o2.stat, o2.threshold, o2.window_s) == ("rate", 0.01, 30.0)
    assert (o3.stat, o3.op, o3.threshold) == ("last", "<=", 32.0)


@pytest.mark.parametrize("bad", [
    "serve.ttft:p42 < 1ms",          # unknown stat
    "serve.ttft < ",                 # missing value
    "serve.ttft:p99 < -3ms",         # nonpositive threshold
    "serve.ttft:p99 < 1ms over 0s",  # zero window
    "epoch.loss:p50 finite",         # finite takes no stat
    "what even is this",
])
def test_slo_grammar_rejects_junk(bad):
    with pytest.raises(ValueError):
        parse_slo_spec(bad)


def test_slo_from_env_inline_and_file(tmp_path, monkeypatch):
    assert SloEngine.from_env(env={}) is None
    eng = SloEngine.from_env(env={"SLO_SPEC": "a.b:rate < 5 over 10s"})
    assert eng.objectives[0].metric == "a.b"
    assert eng.retain_s() == pytest.approx(50.0)  # long window factor
    spec = tmp_path / "slo.spec"
    spec.write_text("# fleet SLOs\nserve.ttft:p99 < 250ms over 20s\n")
    eng = SloEngine.from_env(env={"SLO_SPEC": str(spec)})
    assert eng.objectives[0].window_s == 20.0


def _span_burst(agg, name, dur, t0, n=20, spacing=0.1):
    for i in range(n):
        agg.add({"kind": "span", "name": name, "dur": dur,
                 "wall": t0 + i * spacing})


def test_slo_multiwindow_burn_breach_and_fast_recovery():
    emitted = []
    eng = SloEngine(
        parse_slo_spec("s:p99 < 10ms over 10s"), long_factor=5.0,
        emit=lambda name, **kw: emitted.append((name, kw)),
    )
    agg = WindowedAggregator(10.0, slice_s=1.0, retain_s=eng.retain_s())
    # Slow history is CLEAN; a short spike alone must not breach (the
    # long window vetoes one-sample pages)...
    _span_burst(agg, "s", 0.002, t0=1000.0, n=300, spacing=0.1)
    _span_burst(agg, "s", 0.100, t0=1031.0, n=3, spacing=0.1)
    st = eng.evaluate(agg, now=1032.0)[0]
    assert st["burn"] > 1.0  # short window IS hot...
    assert not st["burning"]  # ...but long window still holds the p99
    assert emitted == []
    # ...until the breach sustains long enough to own the long window.
    _span_burst(agg, "s", 0.100, t0=1032.0, n=100, spacing=0.1)
    st = eng.evaluate(agg, now=1042.0)[0]
    assert st["burning"] and st["burn_long"] > 1.0
    assert [e[0] for e in emitted] == ["slo_breach"]
    assert emitted[0][1]["burn"] == pytest.approx(st["burn"], rel=0.01)
    # Recovery needs only the SHORT window clean — fast all-clear.
    st = eng.evaluate(agg, now=1060.0)[0]
    assert not st["burning"]
    assert [e[0] for e in emitted] == ["slo_breach", "slo_recover"]
    assert st["worst_burn"] > 1.0  # the engine remembers the worst
    assert st["breaches"] == 1


def test_slo_finite_objective_and_rate():
    emitted = []
    eng = SloEngine(
        parse_slo_spec("epoch.loss finite; err:rate < 1% over 10s"),
        emit=lambda name, **kw: emitted.append((name, kw)),
    )
    agg = WindowedAggregator(10.0, slice_s=1.0, retain_s=eng.retain_s())
    agg.add({"kind": "gauge", "name": "epoch.loss", "value": 1.25,
             "wall": 1000.0})
    sts = eng.evaluate(agg, now=1000.0)
    assert not sts[0]["burning"] and sts[0]["burn"] == 0.0
    agg.add({"kind": "gauge", "name": "epoch.loss", "value": float("nan"),
             "wall": 1001.0})
    sts = eng.evaluate(agg, now=1001.0)
    assert sts[0]["burning"] and sts[0]["burn"] == BURN_MAX
    assert emitted[0][0] == "slo_breach"
    # rate: 2 events over the 10s window = 0.2/s vs 0.01/s threshold
    agg.add({"kind": "counter", "name": "err", "value": 2, "wall": 1002.0})
    sts = eng.evaluate(agg, now=1002.0)
    assert sts[1]["burn"] == pytest.approx(20.0)


def test_slo_points_land_on_the_global_bus(tmp_path):
    bus = obs.configure(str(tmp_path), run_id="r-slo")
    eng = SloEngine(parse_slo_spec("s:p99 < 1ms over 5s"))
    agg = WindowedAggregator(5.0, slice_s=0.5, retain_s=eng.retain_s())
    _span_burst(agg, "s", 0.5, t0=100.0, n=30, spacing=0.1)
    eng.evaluate(agg, now=103.0)
    bus.flush()
    events = [json.loads(ln) for ln in open(bus.path)][1:]
    breach = [e for e in events if e["name"] == "slo_breach"]
    assert breach and breach[0]["labels"]["objective"] == "s:p99 < 1ms over 5s"


# ---------------------------------------------------------------------------
# LivePlane: tail -> rollup -> SLO -> rollup.json
# ---------------------------------------------------------------------------

def test_live_plane_end_to_end_over_bus_files(tmp_path):
    bus = obs.configure(str(tmp_path), run_id="r-plane")
    eng = SloEngine(parse_slo_spec("serve.ttft:p99 < 1ms over 5s"))
    plane = LivePlane(str(tmp_path), window_s=5.0, slice_s=0.5,
                      slo_engine=eng)
    t0 = time.monotonic()
    for i in range(10):
        bus.span_event("serve.ttft", 0.05, t=t0 + i * 0.01)
        bus.counter("serve.tokens", 3)
    bus.gauge("serve.slot_occupancy", 0.75)
    bus.flush()
    snap = plane.poll()
    assert snap["spans"]["serve.ttft"]["count"] == 10
    assert snap["counters"]["serve.tokens"]["sum"] == 30.0
    assert snap["gauges"]["serve.slot_occupancy"]["value"] == 0.75
    assert snap["slo"][0]["burning"]
    # the published file is the same consistent view
    disk = read_snapshot(os.path.join(str(tmp_path), "rollup.json"))
    assert disk["slo"][0]["burning"] is True
    assert disk["spans"]["serve.ttft"]["count"] == 10


# ---------------------------------------------------------------------------
# Admission feedback (serving/scheduler.py) — deterministic unit
# ---------------------------------------------------------------------------

def _fake_server(prefills=4, depth=64):
    return types.SimpleNamespace(
        prefills_per_step=prefills, queue_depth=depth, queue_limit=depth,
    )


def _slo_status(burning, stat="p99", metric="serve.ttft"):
    return {"objective": f"{metric}:{stat} < 250ms over 60s",
            "metric": metric, "stat": stat, "burning": burning,
            "burn": 2.0 if burning else 0.5}


def test_adaptive_policy_derates_and_restores_deterministically(tmp_path):
    from distributeddeeplearning_tpu.serving.scheduler import (
        AdaptiveAdmissionPolicy,
    )

    bus = obs.configure(str(tmp_path), run_id="r-pol")
    snaps = [
        None,                                  # plane not up yet: static
        {"slo": [_slo_status(True)]},          # latency SLO burning
        {"slo": [_slo_status(True)]},          # still burning: no re-derate
        {"slo": [_slo_status(False)]},         # recovered
    ]
    it = iter(snaps)
    pol = AdaptiveAdmissionPolicy(
        reader=lambda: next(it), refresh_s=0.0, derate_prefills=1,
        derate_queue_frac=0.5,
    )
    srv = _fake_server(prefills=4, depth=64)
    pol.tick(srv, now=1.0)
    assert (srv.prefills_per_step, srv.queue_limit) == (4, 64)
    pol.tick(srv, now=2.0)
    assert (srv.prefills_per_step, srv.queue_limit) == (1, 32)
    assert pol.derated
    pol.tick(srv, now=3.0)  # idempotent while burning
    assert (srv.prefills_per_step, srv.queue_limit) == (1, 32)
    pol.tick(srv, now=4.0)
    assert (srv.prefills_per_step, srv.queue_limit) == (4, 64)
    assert not pol.derated
    bus.flush()
    events = [json.loads(ln) for ln in open(bus.path)][1:]
    names = [e["name"] for e in events]
    assert names.index("serve.admission_derate") < names.index(
        "serve.admission_restore"
    )
    prefill_gauges = [
        e["value"] for e in events
        if e["name"] == "serve.admission_prefills"
    ]
    assert prefill_gauges == [1.0, 4.0]  # lowered, then restored


def test_adaptive_policy_ignores_non_latency_objectives():
    from distributeddeeplearning_tpu.serving.scheduler import (
        AdaptiveAdmissionPolicy,
    )

    pol = AdaptiveAdmissionPolicy(
        reader=lambda: {"slo": [_slo_status(True, stat="rate")]},
        refresh_s=0.0,
    )
    srv = _fake_server()
    pol.tick(srv, now=1.0)
    assert not pol.derated  # a burning THROUGHPUT slo must not shed load
    # and the latency filter can be narrowed by metric prefix
    pol2 = AdaptiveAdmissionPolicy(
        reader=lambda: {"slo": [_slo_status(True, metric="train.step")]},
        refresh_s=0.0, watch_prefix="serve.",
    )
    pol2.tick(srv, now=1.0)
    assert not pol2.derated


def test_serve_config_admission_policy_env(tmp_path, monkeypatch):
    from distributeddeeplearning_tpu.serving import ServeConfig
    from distributeddeeplearning_tpu.serving.scheduler import (
        AdaptiveAdmissionPolicy,
    )

    assert ServeConfig.from_env(env={}).build_admission_policy() is None
    cfg = ServeConfig.from_env(env={
        "SERVE_ADMISSION_POLICY": "adaptive",
        "SERVE_ROLLUP_PATH": str(tmp_path / "ro.json"),
    })
    pol = cfg.build_admission_policy()
    assert isinstance(pol, AdaptiveAdmissionPolicy)
    assert pol.snapshot_path == str(tmp_path / "ro.json")
    # default path: $OBS_DIR/rollup.json
    monkeypatch.setenv("OBS_DIR", str(tmp_path))
    cfg = ServeConfig.from_env(env={"SERVE_ADMISSION_POLICY": "adaptive"})
    assert cfg.build_admission_policy().snapshot_path == os.path.join(
        str(tmp_path), "rollup.json"
    )
    with pytest.raises(ValueError):
        ServeConfig.from_env(
            env={"SERVE_ADMISSION_POLICY": "wat"}
        ).build_admission_policy()


# ---------------------------------------------------------------------------
# END-TO-END oracle: shed-then-recover, asserted from the merged stream
# ---------------------------------------------------------------------------

def test_server_sheds_then_recovers_under_injected_slo_breach(tmp_path):
    """The acceptance oracle (ISSUE 7): a real SlotEngine server under a
    live plane + an SLO guaranteed to breach (ttft p99 < 0.01ms — any
    real prefill violates it). The plane's rollup feeds the adaptive
    admission policy; the merged event stream must show
    slo_breach -> serve.admission_derate (gauge lowered) ->
    slo_recover -> serve.admission_restore (gauge restored)."""
    import jax
    import jax.numpy as jnp

    from distributeddeeplearning_tpu.models.transformer_lm import (
        TransformerLM,
    )
    from distributeddeeplearning_tpu.serving import Request, Server, SlotEngine
    from distributeddeeplearning_tpu.serving.scheduler import (
        AdaptiveAdmissionPolicy,
    )

    vocab, max_len = 64, 16
    model = TransformerLM(variant="tiny", vocab_size=vocab,
                          max_seq_len=max_len, dtype=jnp.float32)
    import flax.linen as nn

    params = nn.unbox(model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, max_len), jnp.int32),
        train=False,
    )["params"])

    bus = obs.configure(str(tmp_path), run_id="r-e2e")
    slo = SloEngine(parse_slo_spec("serve.ttft:p99 < 0.01ms over 1s"))
    plane = LivePlane(str(tmp_path), window_s=1.0, slice_s=0.25,
                      slo_engine=slo)
    policy = AdaptiveAdmissionPolicy(
        snapshot_path=plane.snapshot_path, refresh_s=0.0,
        derate_prefills=1, derate_queue_frac=0.5,
    )
    engine = SlotEngine(model, params, num_slots=2, max_len=max_len,
                        buckets=(4,))
    engine.warmup()
    server = Server(engine, queue_depth=8, prefills_per_step=2,
                    admission_policy=policy)
    rng = np.random.RandomState(0)
    for _ in range(6):
        server.submit(Request(
            prompt=rng.randint(0, vocab, size=(3,)).astype(np.int32),
            max_new_tokens=6,
        ))
    # Pump scheduler and plane in lockstep: every tick flushes the bus,
    # the plane tails + evaluates, the NEXT tick's policy read sees it.
    while server.step():
        bus.flush()
        plane.poll(now=time.time())
    assert policy.derated  # breach arrived while work was in flight
    assert server.prefills_per_step == 1 and server.queue_limit == 4
    # Traffic stopped: let the short SLO window drain, then one more
    # tick so the policy reads the recovered snapshot.
    deadline = time.time() + 10.0
    while slo.any_burning and time.time() < deadline:
        time.sleep(0.15)
        bus.flush()
        plane.poll(now=time.time())
    assert not slo.any_burning
    server.step()  # policy tick on the recovered rollup
    assert not policy.derated
    assert server.prefills_per_step == 2 and server.queue_limit == 8
    bus.flush()

    # The whole story must be reconstructible from the merged stream.
    merged = obs_report.merge_run_dir(str(tmp_path))
    events = [json.loads(ln) for ln in open(merged)]
    names = [e["name"] for e in events if e.get("kind") != "meta"]
    seq = [n for n in names if n in (
        "slo_breach", "serve.admission_derate", "slo_recover",
        "serve.admission_restore",
    )]
    assert seq == ["slo_breach", "serve.admission_derate",
                   "slo_recover", "serve.admission_restore"]
    gauges = [
        (e["name"], e["value"]) for e in events
        if e.get("kind") == "gauge"
        and e["name"] == "serve.admission_prefills"
    ]
    assert gauges == [("serve.admission_prefills", 1.0),
                      ("serve.admission_prefills", 2.0)]
    # every submitted request still finished (shed slows admission;
    # it never corrupts or drops admitted work)
    assert server.stats["completed"] == 6
    # and the post-hoc report renders the same story as an SLO section
    summary = obs_report.summarize(obs_report.load([str(tmp_path)]))
    slo_sec = summary["slo"]["serve.ttft:p99 < 0.01ms over 1s"]
    assert slo_sec["breaches"] == 1 and slo_sec["recovers"] == 1
    assert slo_sec["worst_burn"] > 1.0
    assert "SLO (breach/recover timeline" in obs_report.render(summary)


# ---------------------------------------------------------------------------
# obs_watch CLI (--once / --json)
# ---------------------------------------------------------------------------

def _synthetic_serving_run(tmp_path):
    bus = obs.configure(str(tmp_path), run_id="r-watch")
    t0 = time.monotonic()
    for i in range(20):
        bus.span_event("serve.ttft", 0.040, t=t0 + i * 0.01)
        bus.counter("serve.tokens", 4)
    bus.gauge("serve.slot_occupancy", 0.5)
    bus.flush()
    obs.reset()


def test_obs_watch_once_renders_rollups_and_slo(tmp_path, capsys):
    from scripts.obs_watch import main as watch_main

    _synthetic_serving_run(tmp_path)
    rc = watch_main([
        str(tmp_path), "--once",
        "--slo", "serve.ttft:p99 < 1ms over 60s; serve.ttft:p50 < 1s",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "SLO objectives" in out
    assert "BURNING" in out and "[ok" in out
    assert "serve.ttft" in out and "serve.tokens" in out
    # --once published the snapshot other components read
    snap = read_snapshot(os.path.join(str(tmp_path), "rollup.json"))
    assert snap["spans"]["serve.ttft"]["count"] == 20
    # --json mode is machine-readable
    rc = watch_main([str(tmp_path), "--json", "--no-write"])
    assert rc == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["counters"]["serve.tokens"]["sum"] == 80.0


def test_obs_watch_rejects_missing_dir(tmp_path, capsys):
    from scripts.obs_watch import main as watch_main

    assert watch_main([str(tmp_path / "nope"), "--once"]) == 2


# ---------------------------------------------------------------------------
# bench_trend CLI (regression sentinel satellite)
# ---------------------------------------------------------------------------

def _trend_file(tmp_path, n, value, *, tier=None, error=None,
                platform="tpu"):
    rec = {"metric": "m", "value": value, "unit": "u", "vs_baseline": 1.0,
           "detail": {"platform": platform}}
    if tier:
        rec["tier"] = tier
    if error:
        rec["error"] = error
    with open(tmp_path / f"BENCH_r{n:02d}.json", "w") as fh:
        json.dump({"n": n, "rc": 1 if error else 0, "parsed": rec}, fh)


def test_bench_trend_skips_outage_tiers_and_flags_real_drops(tmp_path):
    from scripts.bench_trend import analyze, main as trend_main

    _trend_file(tmp_path, 1, 100.0)
    _trend_file(tmp_path, 2, 0.0, tier="outage",
                error="relay down")          # must NOT read as -100%
    _trend_file(tmp_path, 3, 40.0, tier="cpu", platform="cpu")  # fallback
    _trend_file(tmp_path, 4, 95.0)           # -5% vs r1: fine
    result = analyze(sorted(map(str, tmp_path.glob("BENCH_r*.json"))))
    assert result["ok"]
    skips = {r["round"]: r["skip"] for r in result["rows"]}
    assert skips[2] == "tier:outage" and skips[3] == "tier:cpu"
    assert result["rows"][3]["delta_pct"] == pytest.approx(-5.0)
    # now a real like-for-like drop
    _trend_file(tmp_path, 5, 80.0)           # -15.8% vs r4
    rc = trend_main(["--glob", str(tmp_path / "BENCH_r*.json")])
    assert rc == 1
    result = analyze(sorted(map(str, tmp_path.glob("BENCH_r*.json"))))
    assert result["regressions"][0]["drop_pct"] == pytest.approx(
        15.79, abs=0.01
    )
    # legacy outage records (error, no tier) are skipped too
    _trend_file(tmp_path, 6, 0.0, error="probe timeout")
    result = analyze(sorted(map(str, tmp_path.glob("BENCH_r*.json"))))
    assert result["rows"][-1]["skip"] == "error"


def test_bench_trend_spec_k_change_is_skip_not_regression(tmp_path):
    """A spec_k protocol change (speculative tier on/off or re-tuned)
    is a new baseline — same treatment as a dtype change; absent spec_k
    (pre-speculation records) normalizes to 0 and stays comparable."""
    from scripts.bench_trend import analyze

    _trend_file(tmp_path, 1, 100.0)          # pre-spec record: spec_k=0
    _trend_file(tmp_path, 2, 98.0)           # still comparable
    with open(tmp_path / "BENCH_r03.json", "w") as fh:
        json.dump({"n": 3, "rc": 0, "parsed": {
            "metric": "m", "value": 60.0, "unit": "u",
            "detail": {"platform": "tpu", "spec_k": 4},
        }}, fh)
    result = analyze(sorted(map(str, tmp_path.glob("BENCH_r*.json"))))
    assert result["ok"]  # the -39% "drop" is a protocol change
    assert result["rows"][2]["skip"] == "spec_change:k=0->k=4"
    # and the new spec protocol becomes its own comparable baseline
    with open(tmp_path / "BENCH_r04.json", "w") as fh:
        json.dump({"n": 4, "rc": 0, "parsed": {
            "metric": "m", "value": 30.0, "unit": "u",
            "detail": {"platform": "tpu", "spec_k": 4},
        }}, fh)
    result = analyze(sorted(map(str, tmp_path.glob("BENCH_r*.json"))))
    assert not result["ok"]  # -50% like-for-like at spec_k=4 IS real


def test_bench_trend_real_trajectory_is_clean():
    """The repo's own BENCH_r*.json history must parse and pass — rounds
    4-5 (relay outage) read as skips, not 100% regressions."""
    from scripts.bench_trend import main as trend_main

    assert trend_main([]) == 0


# ---------------------------------------------------------------------------
# Report SLO section (post-hoc satellite)
# ---------------------------------------------------------------------------

def test_report_summarize_builds_slo_timeline(tmp_path):
    bus = EventBus(directory=str(tmp_path), proc=0, run_id="r-rep")
    bus.point("slo_breach", objective="o1", burn=3.2, value=0.8)
    bus.point("slo_recover", objective="o1", burn=0.4, value=0.1)
    bus.point("slo_breach", objective="o2", burn=1.5, value=9)
    bus.close()
    summary = obs_report.summarize(obs_report.load([str(tmp_path)]))
    o1 = summary["slo"]["o1"]
    assert o1["breaches"] == 1 and o1["recovers"] == 1
    assert o1["worst_burn"] == pytest.approx(3.2)
    assert [e["event"] for e in o1["timeline"]] == ["breach", "recover"]
    assert summary["slo"]["o2"]["breaches"] == 1
    text = obs_report.render(summary)
    assert "STILL BREACHED" in text  # o2 never recovered
    assert "worst burn 3.20x" in text
    # runs without SLO events render no section
    assert obs_report.summarize(
        obs_report.load([str(tmp_path)])
    )["slo"] is not None
