"""Fleet serving oracles (serving/fleet/ — router, replicas, streaming).

The fleet tier's claims, each pinned here:

1. **Weighted fairness** — deficit round robin dispatches tokens in
   weight proportion under contention, and a weight-1 tenant still
   progresses under a hot neighbour (no starvation).
2. **Zero-drop drain / fault re-route** — draining a replica mid-load
   completes or re-routes every in-flight/queued request; a *faulted*
   replica's running requests restart elsewhere and the fleet handle
   splices the replayed stream bitwise (per-request determinism is the
   serving tier's contract; the splice oracle checks it survived).
3. **Prefix-affinity placement** — a request sharing a cached prompt
   prefix routes to the replica whose BlockAllocator holds the blocks,
   and its prefill computes only the divergent suffix.
4. **Streaming** — ``stream()`` / ``on_token`` deliver exactly the
   final token sequence, incrementally, at Server, Router and
   ``generate(engine=)`` level.
5. **Autoscale** — the pressure gauge rises with backlog and the
   controller's watermark hysteresis adds/drains/removes replicas.

Engines are tiny (64-vocab lm) and replicas are pumped inline
(threaded=False) wherever determinism matters.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.inference import generate
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.serving import (
    ControllerConfig,
    FleetConfig,
    FleetController,
    QueueFull,
    Replica,
    Request,
    Router,
    ServeConfig,
    Server,
    SlotEngine,
)
from distributeddeeplearning_tpu.serving.fleet.router import (
    parse_tenant_weights,
)

VOCAB, MAX_LEN = 64, 32


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    import flax.linen as nn

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


def _scfg(**over):
    kw = dict(num_slots=2, buckets=(8,), prefills_per_step=2)
    kw.update(over)
    return ServeConfig(**kw)


@pytest.fixture(scope="module")
def _pair(model, params):
    """Two warmed inline replicas shared across the non-destructive
    tests (engine compiles amortized module-wide)."""
    reps = [
        Replica(k, model, params, _scfg(), max_len=MAX_LEN).start(
            threaded=False
        )
        for k in range(2)
    ]
    return reps


@pytest.fixture
def fleet(_pair):
    """A fresh router over the shared replicas, verified idle."""
    for r in _pair:
        assert r.state == "ready" and r.server.active_count == 0, (
            "previous test left the shared replicas dirty"
        )
    router = Router(config=FleetConfig(replicas=2, quantum=8))
    for r in _pair:
        r.dispatched = 0
        router.add_replica(r, start=False)
    return router


def _prompt(rng, n=5):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _ref(model, params, prompt, max_new, **kw):
    return np.asarray(
        generate(model, params, np.asarray(prompt)[None],
                 max_new_tokens=max_new, **kw)
    )[0]


# -- config / parsing ----------------------------------------------------


def test_parse_tenant_weights():
    assert parse_tenant_weights("a:3,b:1.5, c ,d:1") == {
        "a": 3.0, "b": 1.5, "c": 1.0, "d": 1.0,
    }


def test_fleet_config_from_env_and_validation():
    env = {
        "SERVE_REPLICAS": "3",
        "SERVE_TENANT_WEIGHTS": "gold:4,base:1",
        "SERVE_PLACEMENT": "rr",
        "SERVE_FLEET_QUEUE_DEPTH": "9",
        "SERVE_FLEET_QUANTUM": "5",
    }
    cfg = FleetConfig.from_env(env)
    assert cfg.replicas == 3
    assert cfg.tenant_weights == {"gold": 4.0, "base": 1.0}
    assert cfg.placement == "rr"
    assert cfg.queue_depth == 9 and cfg.quantum == 5
    cfg.validate()
    with pytest.raises(ValueError):
        FleetConfig(placement="nope").validate()
    with pytest.raises(ValueError):
        FleetConfig(replicas=0).validate()
    with pytest.raises(ValueError):
        FleetConfig(tenant_weights={"a": 0.0}).validate()


# -- fairness ------------------------------------------------------------


def test_weighted_fair_dispatch_shares(fleet):
    """Token-cost DRR: at the instant the heavy tenant's backlog
    empties, dispatched token totals track the 3:1 weights."""
    fleet.config.tenant_weights = {"a": 3.0, "b": 1.0}
    fleet.set_tenant_weight("a", 3.0)
    fleet.set_tenant_weight("b", 1.0)
    rng = np.random.RandomState(0)
    by_tenant = {"a": [], "b": []}
    for i in range(12):
        for t in ("a", "b"):
            by_tenant[t].append(fleet.submit(Request(
                prompt=_prompt(rng), max_new_tokens=4, temperature=0.0,
            ), tenant=t))
    dispatched_at_trigger = None
    for _ in range(4000):
        busy = fleet.step()
        stats = fleet.tenant_stats()
        if dispatched_at_trigger is None and stats["a"]["queued"] == 0:
            dispatched_at_trigger = {
                t: sum(1 for fh in hs if fh.attempts > 0)
                for t, hs in by_tenant.items()
            }
        if not busy:
            break
    assert dispatched_at_trigger is not None
    # a dispatched all 12; b's share of the window is 12/3 = 4 +- burst
    assert dispatched_at_trigger["a"] == 12
    assert 2 <= dispatched_at_trigger["b"] <= 6, dispatched_at_trigger
    for hs in by_tenant.values():
        for fh in hs:
            assert fh.finish_reason == "length"


def test_no_starvation_under_hot_neighbour(fleet):
    """A weight-16 flood cannot starve a weight-1 tenant: the small
    tenant banks deficit every cursor cycle and completes work while
    the flood is still backlogged."""
    fleet.set_tenant_weight("hot", 16.0)
    fleet.set_tenant_weight("cold", 1.0)
    rng = np.random.RandomState(1)
    hot = [
        fleet.submit(Request(
            prompt=_prompt(rng), max_new_tokens=4, temperature=0.0,
        ), tenant="hot")
        for _ in range(24)
    ]
    cold = fleet.submit(Request(
        prompt=_prompt(rng), max_new_tokens=4, temperature=0.0,
    ), tenant="cold")
    for _ in range(4000):
        if cold.done.is_set() or not fleet.step():
            break
    assert cold.done.is_set() and cold.finish_reason == "length"
    # the flood must still be mid-backlog when the small tenant finished
    assert fleet.tenant_stats()["hot"]["queued"] > 0
    fleet.drain(timeout=300)
    assert all(h.finish_reason == "length" for h in hot)


# -- parity + placement --------------------------------------------------


def test_fleet_parity_and_least_loaded_spread(fleet, model, params):
    """Requests served across 2 replicas are bitwise what sequential
    generate produces, and least-loaded placement uses both pools."""
    rng = np.random.RandomState(2)
    cases = []
    for i in range(8):
        p = _prompt(rng)
        cases.append((p, fleet.submit(Request(
            prompt=p, max_new_tokens=6, temperature=0.8, top_k=8, rng=i,
        ))))
    fleet.drain(timeout=300)
    for i, (p, fh) in enumerate(cases):
        ref = _ref(model, params, p, 6, temperature=0.8, top_k=8,
                   rng=jax.random.PRNGKey(i))
        np.testing.assert_array_equal(fh.result(timeout=0), ref)
    used = {fh.replica_id for _, fh in cases}
    assert used == {0, 1}, f"placement collapsed onto {used}"


def test_queue_full_backpressure(model, params, fleet):
    fleet.config.queue_depth = 3
    rng = np.random.RandomState(3)
    handles = [
        fleet.submit(Request(prompt=_prompt(rng), max_new_tokens=2))
        for _ in range(3)
    ]
    with pytest.raises(QueueFull):
        fleet.submit(Request(prompt=_prompt(rng), max_new_tokens=2))
    fleet.drain(timeout=300)
    assert all(h.finish_reason == "length" for h in handles)


# -- streaming -----------------------------------------------------------


def test_stream_iterator_matches_final_tokens(model, params):
    """Server-level pull streaming: the iterator yields exactly the
    final token sequence, incrementally, while another thread pumps."""
    engine = SlotEngine(
        model, params, num_slots=2, max_len=MAX_LEN, buckets=(8,)
    )
    engine.warmup()
    server = Server(engine, prefills_per_step=2)
    rng = np.random.RandomState(4)
    p = _prompt(rng)
    seen = []
    h = server.submit(Request(
        prompt=p, max_new_tokens=8, temperature=0.0,
        on_token=lambda _h, toks: seen.extend(toks),
    ))
    stop = threading.Event()
    pump = threading.Thread(target=server.serve_forever, args=(stop,))
    pump.start()
    try:
        streamed = list(h.stream(timeout=60))
    finally:
        stop.set()
        pump.join(timeout=60)
    assert streamed == h.new_tokens == seen
    ref = _ref(model, params, p, 8)
    np.testing.assert_array_equal(h.tokens, ref)


def test_generate_engine_route_streams_on_token(fleet, model, params):
    """generate(engine=router) returns the reference tokens AND streams
    them through on_token in row order, exactly once each."""
    rng = np.random.RandomState(5)
    prompts = np.stack([_prompt(rng, 6), _prompt(rng, 6)])
    got_stream = {0: [], 1: []}
    out = generate(
        model, params, prompts, max_new_tokens=5,
        engine=fleet, on_token=lambda row, tok: got_stream[row].append(tok),
    )
    for b in range(2):
        np.testing.assert_array_equal(
            out[b], np.concatenate([
                prompts[b], np.asarray(got_stream[b], np.int32)
            ]),
        )
    ref0 = _ref(model, params, prompts[0], 5)
    np.testing.assert_array_equal(out[0], ref0)


def test_on_token_requires_engine(model, params):
    with pytest.raises(ValueError, match="on_token"):
        generate(
            model, params, np.zeros((1, 4), np.int32), max_new_tokens=2,
            on_token=lambda row, tok: None,
        )


# -- drain / fault / rejoin ----------------------------------------------


def test_drain_mid_load_completes_everything(fleet, model, params):
    """E2E zero-drop oracle: drain a replica mid-load; every request
    still completes with the reference stream; the drained replica
    parks; rejoin serves again."""
    rng = np.random.RandomState(6)
    cases = []
    for i in range(10):
        p = _prompt(rng)
        cases.append((p, fleet.submit(Request(
            prompt=p, max_new_tokens=6, temperature=0.0,
        ))))
    # start streams, then drain replica 0 mid-load
    for _ in range(2):
        fleet.step()
    fleet.drain_replica(0)
    fleet.drain(timeout=300)
    for p, fh in cases:
        ref = _ref(model, params, p, 6)
        np.testing.assert_array_equal(fh.result(timeout=0), ref)
        assert fh.finish_reason == "length"
    r0 = fleet._replica(0)
    assert r0.state == "drained"
    assert fleet.stats["completed"] == len(cases)
    # rejoin (clean drain keeps the warmed engine: same program set)
    programs_before = r0.engine.compile_count
    fleet.rejoin_replica(0, threaded=False)
    assert r0.state == "ready"
    assert r0.engine.compile_count == programs_before
    p = _prompt(rng)
    h = fleet.submit(Request(prompt=p, max_new_tokens=3))
    fleet.drain(timeout=300)
    assert h.finish_reason == "length"


def test_fault_reroutes_running_and_splices_bitwise(model, params):
    """A replica whose pump dies mid-decode: its running requests
    restart on the survivor and the delivered streams stay bitwise the
    references — the splice never duplicates or diverges."""
    reps = [
        Replica(k, model, params, _scfg(), max_len=MAX_LEN).start(
            threaded=False
        )
        for k in range(2)
    ]
    router = Router(config=FleetConfig(replicas=2, quantum=64))
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(7)
    cases = []
    for i in range(6):
        p = _prompt(rng)
        cases.append((p, router.submit(Request(
            prompt=p, max_new_tokens=10, temperature=0.0,
        ))))
    for _ in range(3):
        router.step()
    r0 = router._replica(0)
    assert r0.server.active_count > 0, "nothing started on replica 0"
    delivered_before = {
        fh.id: list(fh.new_tokens) for _, fh in cases
    }
    r0.engine.decode_step = lambda: (_ for _ in ()).throw(
        RuntimeError("injected engine fault")
    )
    router.step()  # this tick's pump faults the replica...
    assert r0.state == "faulted"
    assert r0.retryable  # generic crash classifies retryable (125)
    router.step()  # ...and the next tick's health sweep re-routes
    assert router.stats["requeued"] > 0
    router.drain(timeout=300)
    for i, (p, fh) in enumerate(cases):
        ref = _ref(model, params, p, 10)
        np.testing.assert_array_equal(fh.result(timeout=0), ref)
        assert fh.restart_consistent, "splice diverged from determinism"
        # tokens delivered before the fault were never re-emitted:
        assert fh.new_tokens[: len(delivered_before[fh.id])] == (
            delivered_before[fh.id]
        )
    # rejoin rebuilds the engine from scratch after a fault
    router.rejoin_replica(0, threaded=False)
    assert r0.state == "ready" and r0.fault is None
    h = router.submit(Request(prompt=cases[0][0], max_new_tokens=3))
    router.drain(timeout=300)
    assert h.finish_reason == "length"


# -- prefix affinity -----------------------------------------------------


def test_prefix_affinity_routes_to_owning_replica(model, params):
    """Paged fleet: a request sharing a cached block-aligned prefix
    routes to the replica already holding those blocks, and its prefill
    starts at the shared boundary (suffix-only compute)."""
    scfg = _scfg(
        kv_layout="paged", block_size=4, num_blocks=64,
        prefix_cache=True, buckets=(16, 32),
    )
    reps = [
        Replica(k, model, params, scfg, max_len=MAX_LEN).start(
            threaded=False
        )
        for k in range(2)
    ]
    router = Router(config=FleetConfig(replicas=2, placement="affinity"))
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(8)
    shared = _prompt(rng, 12)
    h1 = router.submit(Request(prompt=shared, max_new_tokens=3))
    router.drain(timeout=300)
    owner = h1.replica_id
    assert owner is not None
    other = 1 - owner
    assert router._replica(owner).prefix_hit_blocks(shared) > 0
    assert router._replica(other).prefix_hit_blocks(shared) == 0
    # a prompt extending the shared prefix routes to the owner...
    p2 = np.concatenate([shared, _prompt(rng, 6)])
    h2 = router.submit(Request(prompt=p2, max_new_tokens=3))
    router.step()
    assert h2.replica_id == owner
    last = router._replica(owner).engine.last_prefill
    assert last["shared_blocks"] > 0 and last["start"] > 0
    router.drain(timeout=300)
    # ...and parity holds through the shared-prefix route
    ref = _ref(model, params, p2, 3)
    np.testing.assert_array_equal(h2.result(timeout=0), ref)
    # an unrelated prompt is NOT affinity-bound (falls to least-loaded)
    h3 = router.submit(Request(prompt=_prompt(rng, 6), max_new_tokens=2))
    router.drain(timeout=300)
    assert h3.finish_reason == "length"
    router.close()


# -- autoscale signal + controller ---------------------------------------


def test_pressure_rises_with_backlog(fleet):
    rng = np.random.RandomState(9)
    assert fleet.pressure() == 0.0
    handles = [
        fleet.submit(Request(prompt=_prompt(rng), max_new_tokens=2))
        for _ in range(12)
    ]
    # 12 demanded over 4 ready slots
    assert fleet.pressure() == pytest.approx(3.0)
    fleet.step()
    assert fleet.last_pressure > 0
    fleet.drain(timeout=300)
    assert fleet.pressure() == 0.0
    assert all(h.finish_reason == "length" for h in handles)


def test_controller_scales_up_and_drains(model, params):
    """Watermark hysteresis on a synthetic pressure trace: sustained
    high pressure adds a replica (factory-built), sustained low drains
    the least-loaded one and removes it once drained."""
    reps = [
        Replica(0, model, params, _scfg(), max_len=MAX_LEN).start(
            threaded=False
        )
    ]
    router = Router(config=FleetConfig(replicas=1))
    router.add_replica(reps[0], start=False)
    built = []

    def factory(rid):
        r = Replica(rid, model, params, _scfg(), max_len=MAX_LEN)
        built.append(rid)
        return r

    trace = iter([2.5, 2.5, 2.5, 0.1, 0.1, 0.1, 0.1, 0.1, 0.1])
    ctl = FleetController(
        router, factory,
        ControllerConfig(min_replicas=1, max_replicas=2,
                         high_pressure=1.0, low_pressure=0.3,
                         up_ticks=3, down_ticks=2),
        reader=lambda: next(trace, None),
        threaded_replicas=False,
    )
    assert ctl.tick() is None
    assert ctl.tick() is None
    assert ctl.tick() == "scale_up"
    assert built == [1]
    assert len(router.replicas) == 2
    assert ctl.tick() is None      # cold 1
    assert ctl.tick() == "drain"   # cold 2 -> drain least-loaded
    drained = [r for r in router.replicas if r.state in (
        "draining", "drained"
    )]
    assert len(drained) == 1
    router.step()  # inline pump parks the empty draining replica
    assert ctl.tick() == "remove"
    assert len(router.replicas) == 1
    assert router.replicas[0].state == "ready"
    router.close()


# -- per-replica observability -------------------------------------------


def test_per_replica_event_streams_and_watch_rows(model, params, tmp_path,
                                                  monkeypatch):
    """Each replica writes its own events-p0-s<k>.jsonl; the rollup
    snapshot grows a per-proc section and obs_watch renders one row per
    replica stream instead of collapsing the gauges."""
    from distributeddeeplearning_tpu import obs
    from distributeddeeplearning_tpu.obs.rollup import LivePlane

    obsdir = str(tmp_path / "run")
    monkeypatch.setenv("OBS_DIR", obsdir)
    obs.configure(obsdir)
    try:
        reps = [
            Replica(k, model, params, _scfg(), max_len=MAX_LEN,
                    obs_dir=obsdir).start(threaded=False)
            for k in range(2)
        ]
        router = Router(config=FleetConfig(replicas=2))
        for r in reps:
            router.add_replica(r, start=False)
        rng = np.random.RandomState(10)
        for _ in range(6):
            router.submit(Request(prompt=_prompt(rng), max_new_tokens=3))
        router.drain(timeout=300)
        obs.flush()
        for r in reps:
            r.bus.flush()
        names = sorted(os.listdir(obsdir))
        assert "events-p0-s0.jsonl" in names
        assert "events-p0-s1.jsonl" in names
        plane = LivePlane(obsdir)
        snap = plane.poll(write=False)
        procs = snap.get("procs")
        assert procs and {"p0-s0", "p0-s1"} <= set(procs)
        for k in ("p0-s0", "p0-s1"):
            assert "serve.slot_occupancy" in procs[k]
            assert "serve.programs" in procs[k]
        # fleet gauges land on the router's (global) stream
        assert "serve.fleet_pressure" in snap["gauges"]
        from scripts.obs_watch import render, replica_rows

        rows = replica_rows(snap)
        assert rows is not None and len(rows) == 2
        text = render(snap)
        assert "serving replicas" in text
        assert "p0-s0" in text and "p0-s1" in text
        router.close()
    finally:
        obs.reset()
