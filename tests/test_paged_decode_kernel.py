"""Fused Pallas paged-decode kernel parity oracles
(``SERVE_DECODE_KERNEL=fused`` — ops/pallas/paged_decode.py).

The fused kernel replaces the stitched XLA decode lowering (gather →
dequantize → mask → softmax → weighted sum) with ONE Pallas program
that walks the slot's block table, dequantizes K/V blocks in-register
and runs online-softmax masked attention. Its contract, pinned here
(CPU tier — the kernel runs in Pallas interpret mode, same program
text as the TPU lowering):

* **Reference parity** — the kernel output matches the XLA decode math
  (``models/vit.Attention._masked_decode_scores``: f32 scores, additive
  min-mask, f32 softmax) to f32 round-off, across the dense row cache,
  the paged block pool, the int8/fp8 quantized stores, and the
  speculative ``[B, K+1]`` verify window.
* **ULP-bounded outputs** — the fused/XLA divergence is reassociation
  only (online vs two-pass softmax), bounded in units-in-last-place,
  not just in loose absolute tolerance.
* **Masking** — positions beyond a row's ``q_pos`` (and beyond
  ``kv_len``) never contribute: garbage planted there — including the
  paged pool's trash block 0 — cannot perturb the output.
* **Vector-position contract** — scalar-index callers (the lockstep
  ``inference.generate`` path) stay on the XLA lowering; the kernel
  rejects ``q_pos`` that is not ``[B, t]``.
* **Engine bitwise parity** — a fused ``SlotEngine`` emits
  token-for-token what the XLA engine emits under greedy decoding (f32
  model: argmax over ULP-equal logits is bitwise), dense and paged,
  int8 and fp8, plain and speculative — with the program set closed at
  the same count on both kernels.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM
from distributeddeeplearning_tpu.ops import quant
from distributeddeeplearning_tpu.ops.pallas.paged_decode import (
    fused_decode_attention,
)
from distributeddeeplearning_tpu.serving import ReqSpec, Request, Server, SlotEngine

B, H, D, L = 2, 4, 32, 16
VOCAB, MAX_LEN = 64, 32
BUCKETS = (4, 8, 16)


def _rand(rng, *shape):
    return jnp.asarray(rng.randn(*shape), jnp.float32)


def _ref_attention(q, k_all, v_all, q_pos, kv_len):
    """The XLA decode math (models/vit.Attention._masked_decode_scores),
    f32 end to end — the oracle the fused kernel must reproduce."""
    d = q.shape[-1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q * d ** -0.5, k_all
    ).astype(jnp.float32)
    k_pos = jnp.arange(k_all.shape[1])
    mask = (
        (k_pos[None, None, :] <= q_pos[:, :, None])
        & (k_pos < kv_len)[None, None, :]
    )
    scores = jnp.where(mask[:, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_all)


def _ulp_distance(a, b):
    """Element-wise f32 ULP distance via the monotone integer mapping
    of IEEE-754 bit patterns (sign-magnitude -> two's-complement)."""

    def mono(x):
        i = np.asarray(x, np.float32).view(np.int32).astype(np.int64)
        return np.where(i < 0, np.int64(-(2 ** 31)) - i, i)

    return np.abs(mono(a) - mono(b))


def _paged_from_dense(dense, block_size, trash=1e4):
    """Scatter a dense [B, L, H, D] cache into a block pool
    [B*mb + 1, block_size, H, D] plus per-row tables; block 0 holds
    garbage (the trash-block convention)."""
    b, length, h, d = dense.shape
    mb = length // block_size
    pool = np.full((b * mb + 1, block_size, h, d), trash, np.float32)
    table = np.zeros((b, mb), np.int32)
    for row in range(b):
        for j in range(mb):
            blk = 1 + row * mb + j
            pool[blk] = np.asarray(
                dense[row, j * block_size:(j + 1) * block_size]
            )
            table[row, j] = blk
    return jnp.asarray(pool), jnp.asarray(table)


def test_dense_row_matches_reference():
    rng = np.random.RandomState(0)
    q = _rand(rng, B, 1, H, D)
    k = _rand(rng, B, L, H, D)
    v = _rand(rng, B, L, H, D)
    pos = jnp.asarray([[5], [L - 1]], jnp.int32)
    out = fused_decode_attention(q, k, v, pos)
    ref = _ref_attention(q, k, v, pos, L)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_dense_outputs_ulp_bounded():
    """Fused vs XLA math differ by softmax reassociation only: every
    output element lands within a small ULP budget of the reference —
    the bound that makes greedy argmax parity a theorem, not luck."""
    rng = np.random.RandomState(1)
    q = _rand(rng, B, 1, H, D)
    k = _rand(rng, B, L, H, D)
    v = _rand(rng, B, L, H, D)
    pos = jnp.full((B, 1), L - 1, jnp.int32)
    out = fused_decode_attention(q, k, v, pos)
    ref = _ref_attention(q, k, v, pos, L)
    assert int(_ulp_distance(out, ref).max()) <= 256


def test_paged_pool_matches_dense():
    rng = np.random.RandomState(2)
    q = _rand(rng, B, 1, H, D)
    k = _rand(rng, B, L, H, D)
    v = _rand(rng, B, L, H, D)
    pos = jnp.asarray([[L - 1], [7]], jnp.int32)
    k_pool, table = _paged_from_dense(k, block_size=4)
    v_pool, _ = _paged_from_dense(v, block_size=4)
    out = fused_decode_attention(
        q, k_pool, v_pool, pos, block_table=table, block_size=4,
    )
    ref = _ref_attention(q, k, v, pos, L)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_trash_block_and_unowned_blocks_never_attended():
    """Table entries past a row's live length point at block 0 (trash);
    masking — not residency — is what keeps them out of the output."""
    rng = np.random.RandomState(3)
    q = _rand(rng, B, 1, H, D)
    k = _rand(rng, B, L, H, D)
    v = _rand(rng, B, L, H, D)
    live = 6  # positions 0..5 live; blocks past ceil(6/4) unassigned
    pos = jnp.full((B, 1), live - 1, jnp.int32)
    k_pool, table = _paged_from_dense(k, block_size=4, trash=1e4)
    v_pool, _ = _paged_from_dense(v, block_size=4, trash=1e4)
    table = np.array(table)
    table[:, 2:] = 0  # unowned tail -> trash block
    out = fused_decode_attention(
        q, k_pool, v_pool, pos, block_table=jnp.asarray(table),
        block_size=4,
    )
    ref = _ref_attention(q, k, v, pos, live)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_kv_len_caps_dense_tail():
    rng = np.random.RandomState(4)
    q = _rand(rng, B, 1, H, D)
    k = _rand(rng, B, L, H, D)
    v = _rand(rng, B, L, H, D)
    kv_len = 10
    poisoned_k = k.at[:, kv_len:].set(1e4)
    poisoned_v = v.at[:, kv_len:].set(1e4)
    pos = jnp.full((B, 1), kv_len - 1, jnp.int32)
    out = fused_decode_attention(q, poisoned_k, poisoned_v, pos,
                                 kv_len=kv_len)
    ref = _ref_attention(q, k, v, pos, kv_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
def test_quantized_store_parity(kv_dtype):
    """Quantized pools: the kernel's in-register dequantize must equal
    attention over the explicitly dequantized store."""
    rng = np.random.RandomState(5)
    q = _rand(rng, B, 1, H, D)
    k = _rand(rng, B, L, H, D)
    v = _rand(rng, B, L, H, D)
    kq, ks = quant.quantize_kv(k, kv_dtype)
    vq, vs = quant.quantize_kv(v, kv_dtype)
    pos = jnp.asarray([[L - 1], [9]], jnp.int32)
    out = fused_decode_attention(q, kq, vq, pos, k_scale=ks, v_scale=vs)
    ref = _ref_attention(
        q,
        quant.dequantize_store(kq, ks, jnp.float32),
        quant.dequantize_store(vq, vs, jnp.float32),
        pos, L,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_spec_verify_window_matches_reference():
    """The [B, K+1] verify view: per-row ascending positions, causal
    within the window — the spec_verify program's attention shape."""
    rng = np.random.RandomState(6)
    kk = 3
    q = _rand(rng, B, kk + 1, H, D)
    k = _rand(rng, B, L, H, D)
    v = _rand(rng, B, L, H, D)
    start = jnp.asarray([4, 9], jnp.int32)
    pos = start[:, None] + jnp.arange(kk + 1)[None, :]
    out = fused_decode_attention(q, k, v, pos)
    ref = _ref_attention(q, k, v, pos, L)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_vector_position_contract_and_scale_pairing():
    rng = np.random.RandomState(7)
    q = _rand(rng, B, 1, H, D)
    k = _rand(rng, B, L, H, D)
    v = _rand(rng, B, L, H, D)
    with pytest.raises(ValueError, match="q_pos"):
        fused_decode_attention(q, k, v, jnp.int32(0))
    with pytest.raises(ValueError, match="q_pos"):
        fused_decode_attention(q, k, v, jnp.zeros((B,), jnp.int32))
    kq, ks = quant.quantize_kv(k, "int8")
    with pytest.raises(ValueError, match="k_scale"):
        fused_decode_attention(q, kq, v, jnp.zeros((B, 1), jnp.int32),
                               k_scale=ks)


# ---------------------------------------------------------------------------
# Engine-level bitwise parity (f32 model: greedy argmax over ULP-equal
# logits is exact)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    import flax.linen as nn

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


def _greedy_streams(engine):
    rng = np.random.RandomState(11)
    server = Server(engine, prefills_per_step=2)
    handles = [
        server.submit(Request(
            prompt=rng.randint(0, VOCAB, size=(n,)).astype(np.int32),
            max_new_tokens=m, temperature=0.0, top_k=None,
        ))
        for n, m in [(3, 6), (7, 8), (12, 4), (16, 6), (5, 9)]
    ]
    server.drain()
    assert all(h.status == "done" for h in handles)
    return [list(h.new_tokens) for h in handles]


def _engine_pair(model, params, **kw):
    engines = []
    for kern in ("xla", "fused"):
        eng = SlotEngine(
            model, params, num_slots=4, max_len=MAX_LEN, buckets=BUCKETS,
            decode_kernel=kern, **kw,
        )
        eng.warmup()
        engines.append(eng)
    return engines


@pytest.mark.parametrize(
    "kw",
    [
        pytest.param({}, id="dense-bf16"),
        pytest.param({"kv_dtype": "int8"}, id="dense-int8"),
        pytest.param(
            {"kv_layout": "paged", "block_size": 4, "kv_dtype": "fp8"},
            id="paged-fp8",
        ),
    ],
)
def test_engine_fused_bitwise_matches_xla(model, params, kw):
    xla, fused = _engine_pair(model, params, **kw)
    assert _greedy_streams(xla) == _greedy_streams(fused)
    # same closed program set on both kernels
    for eng in (xla, fused):
        assert eng.compile_count == eng.programs_expected
        assert eng.programs_expected == len(BUCKETS) + 1


def test_engine_spec_verify_fused_bitwise_matches_xla(model, params):
    xla, fused = _engine_pair(
        model, params, kv_layout="paged", block_size=4, kv_dtype="int8",
        spec_k=2, spec_draft="ngram",
    )
    assert _greedy_streams(xla) == _greedy_streams(fused)
    for eng in (xla, fused):
        assert eng.compile_count == eng.programs_expected


def test_engine_decode_logits_ulp_bounded(model, params):
    """Per-step decode logits from the fused and XLA engines stay
    within a small f32 ULP budget on identical pool state — the claim
    the bitwise token-stream parity rests on."""
    xla, fused = _engine_pair(model, params, kv_dtype="int8")
    prompt = np.arange(1, 7, dtype=np.int32)
    spec = ReqSpec(prompt=prompt, max_new_tokens=4)
    for eng in (xla, fused):
        eng.prefill(0, spec)
    logits = []
    for eng in (xla, fused):
        cache = eng._with_positions(
            eng._pool, jnp.asarray(np.full(4, len(prompt), np.int32))
        )
        out, _ = eng.decode_model.apply(
            {"params": eng._live_params(eng.params), "cache": cache},
            jnp.asarray(np.full(4, 3, np.int32))[:, None],
            train=False, mutable=["cache"],
        )
        logits.append(np.asarray(out[0, -1], np.float32))
    assert int(_ulp_distance(logits[0], logits[1]).max()) <= 1024
