"""Correctness tests for the three attention implementations.

VERDICT round-1 flagged ``impl='pallas'`` and ``impl='ring'`` as phantom
dispatches; these tests pin the now-real implementations to the XLA
reference path (fwd + grads), on the same 8-device CPU mesh the rest of
the suite uses (the Pallas kernel runs in interpreter mode off-TPU, so
the kernel body itself is exercised).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributeddeeplearning_tpu.ops.attention import dot_product_attention
from distributeddeeplearning_tpu.ops.pallas.flash import flash_attention
from distributeddeeplearning_tpu.parallel.mesh import create_mesh
from distributeddeeplearning_tpu.parallel.ring_attention import ring_attention


def _qkv(b=2, t=64, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(
        jnp.asarray(rng.randn(b, t, h, d).astype(np.float32)) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_xla_forward(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal, impl="xla")
    out = dot_product_attention(q, k, v, causal=causal, impl="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_xla_grads(causal):
    q, k, v = _qkv(t=32, d=8)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        loss(lambda q, k, v, causal: dot_product_attention(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_ragged_length():
    """Sequence not divisible by the block size: padding must be masked."""
    q, k, v = _qkv(t=100, d=8)
    ref = dot_product_attention(q, k, v, impl="xla")
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_causal_requires_equal_lengths():
    q, k, v = _qkv(t=32, d=8)
    with pytest.raises(ValueError):
        flash_attention(q, k[:, :16], v[:, :16], causal=True)


def _ring_fn(mesh, causal):
    def ring(q, k, v):
        return ring_attention(q, k, v, axis_name="seq", causal=causal)

    spec = P(None, "seq")
    return jax.jit(
        jax.shard_map(
            ring, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
        )
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_xla(devices, causal):
    mesh = create_mesh(axes=("seq",))
    q, k, v = _qkv()
    out = _ring_fn(mesh, causal)(q, k, v)
    ref = dot_product_attention(q, k, v, causal=causal, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_xla(devices, causal):
    mesh = create_mesh(axes=("seq",))
    q, k, v = _qkv(d=8)
    f = _ring_fn(mesh, causal)
    g_ring = jax.grad(lambda q, k, v: jnp.sum(f(q, k, v) ** 2), argnums=(0, 1, 2))(
        q, k, v
    )
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(
            dot_product_attention(q, k, v, causal=causal) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ring_dispatch_requires_shard_map():
    # impl='ring' defaults to the mesh convention's "seq" axis, which is
    # only bound inside shard_map — outside, jax rejects the axis name.
    q, k, v = _qkv(t=8, d=8)
    with pytest.raises(NameError, match="seq"):
        dot_product_attention(q, k, v, impl="ring")


def test_unknown_impl_raises():
    q, k, v = _qkv(t=8, d=8)
    with pytest.raises(ValueError):
        dot_product_attention(q, k, v, impl="nope")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bwd_kernels_match_scan_reference(causal):
    """The Mosaic backward kernels (dq; dk/dv — round 3) against the
    kept pure-JAX scan backward they replaced, on ragged lengths so the
    q/k padding masks are exercised."""
    from distributeddeeplearning_tpu.ops.pallas.flash import (
        _flash,
        _flash_bwd_rule,
        _flash_bwd_scan,
    )

    rng = np.random.RandomState(3)
    bh, t, d = 2, 70, 8  # t=70: two ragged 64-blocks with padding
    q, k, v = (
        jnp.asarray(rng.randn(bh, t, d).astype(np.float32)) for _ in range(3)
    )
    scale = d**-0.5
    out, lse = _flash(q, k, v, causal, scale, 64, 64, True)
    res = (q, k, v, out[:, :t], lse[:, :t])
    do = jnp.asarray(rng.randn(bh, t, d).astype(np.float32))
    got = _flash_bwd_rule(causal, scale, 64, 64, True, res, do)
    ref = _flash_bwd_scan(causal, scale, 64, 64, True, res, do)
    for name, a, b in zip(("dq", "dk", "dv"), got, ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, err_msg=name
        )


# ---- packed small-T kernel (ops/pallas/flash_packed.py) ----

from distributeddeeplearning_tpu.ops.pallas.flash_packed import (  # noqa: E402
    fused_qkv_attention,
    supports,
)


def _packed_ref(qkv, heads, causal):
    """Independent einsum reference for the packed layout."""
    b, t, thd = qkv.shape
    d = thd // 3 // heads
    q, k, v = [x.reshape(b, t, heads, d) for x in jnp.split(qkv, 3, -1)]
    out = dot_product_attention(q, k, v, causal=causal, impl="xla")
    return out.reshape(b, t, heads * d)


@pytest.mark.parametrize(
    "b,t,h,d,causal",
    [
        (4, 29, 2, 64, False),  # ragged T, two heads per 128-lane block
        (2, 29, 2, 64, True),
        (2, 16, 1, 128, True),  # one head per block
        (3, 48, 4, 32, False),  # four heads per block
    ],
)
def test_packed_matches_xla(b, t, h, d, causal):
    rng = np.random.RandomState(0)
    qkv = jnp.asarray(rng.randn(b, t, 3 * h * d).astype(np.float32))
    out = fused_qkv_attention(qkv, h, causal=causal, interpret=True)
    ref = _packed_ref(qkv, h, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_packed_grads_match_xla(causal):
    rng = np.random.RandomState(1)
    qkv = jnp.asarray(rng.randn(2, 29, 3 * 2 * 64).astype(np.float32))

    def loss(fn):
        return lambda x: jnp.sum(jnp.sin(fn(x)))

    g = jax.grad(
        loss(lambda x: fused_qkv_attention(x, 2, causal=causal, interpret=True))
    )(qkv)
    g_ref = jax.grad(loss(lambda x: _packed_ref(x, 2, causal)))(qkv)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4)


def test_packed_ragged_tail_is_finite():
    """The unpadded ragged tail must be sanitised in-kernel: gradients
    through every contraction over T stay finite (a poisoned tail row
    would NaN dq/dk/dv)."""
    rng = np.random.RandomState(2)
    qkv = jnp.asarray(rng.randn(2, 17, 3 * 2 * 64).astype(np.float32))
    g = jax.grad(
        lambda x: jnp.sum(fused_qkv_attention(x, 2, interpret=True))
    )(qkv)
    assert bool(jnp.all(jnp.isfinite(g)))


def test_packed_supports_gating():
    assert supports(197, 12, 64)
    assert supports(512, 16, 128)
    # long T is the streaming kernel's regime — and at 1024 the ~6 live
    # [T, T] f32 intermediates alone exceed the scoped-VMEM budget
    assert not supports(1024, 16, 128)
    assert not supports(2048, 12, 64)
    assert not supports(197, 3, 64)  # 3 heads don't fill 128-lane blocks
    with pytest.raises(ValueError):
        fused_qkv_attention(jnp.zeros((1, 8, 3 * 3 * 64)), 3, interpret=True)
