"""Streamed data plane oracles (data/stream/, docs/DATA.md).

The contracts under test:

* cursor seek is BITWISE the replayed stream (``epoch_at(e, k)`` ==
  the tail of ``epoch(e)``) — the O(1)-resume foundation;
* the delivered global batch is process-count-independent by
  construction (1/2/4-process slices concatenate to the same batch,
  and a mid-epoch cursor continues bitwise across world sizes) — the
  elastic contract on real data;
* mid-epoch checkpoint/restore through the manifest's ``data_cursor``
  bitwise-continues training with ``data.resume_skip_batches == 0``
  and no O(step) prefix replay;
* host prefetch is math-neutral and adds zero host syncs
  (SyncAccountant);
* shard-index corruption is a clear, file-naming error;
* the pretrain→checkpoint→serve pipeline: a ``SlotEngine`` loaded from
  the restored checkpoint serves greedy tokens equal to
  ``inference.generate``.
"""

import json
import os
import shutil

import jax
import numpy as np
import pytest

from distributeddeeplearning_tpu.data.stream import (
    INDEX_BASENAME,
    BlockShuffle,
    RecordStreamDataset,
    StreamFormatError,
    TokenStreamDataset,
    corpus_to_rows,
    host_prefetch,
    load_index,
    open_stream_dataset,
    synthetic_records,
    synthetic_rows,
    write_record_shards,
    write_token_shards,
)

VOCAB, T = 64, 8


def _token_dir(tmp_path, n=64, seq=T, vocab=VOCAB, shard=16, seed=7):
    d = str(tmp_path / f"tok{n}x{seq}")
    if not os.path.isdir(d):
        write_token_shards(
            d, synthetic_rows(n, seq_len=seq, vocab_size=vocab, seed=seed),
            seq_len=seq, vocab_size=vocab, shard_records=shard,
        )
    return d


# ---------------------------------------------------------------------------
# Index + shard IO
# ---------------------------------------------------------------------------

def test_index_roundtrip_and_ordered_gather(tmp_path):
    rows = synthetic_rows(50, seq_len=T, vocab_size=VOCAB, seed=3)
    d = str(tmp_path / "s")
    meta = write_token_shards(
        d, rows, seq_len=T, vocab_size=VOCAB, shard_records=16
    )
    assert meta["total_records"] == 50
    assert len(meta["shards"]) == 4  # 16+16+16+2
    idx = load_index(d)
    np.testing.assert_array_equal(idx.read("tokens", np.arange(50)), rows)
    # order-preserving gather across shard boundaries, duplicates included
    ids = np.array([49, 0, 17, 17, 33, 2])
    np.testing.assert_array_equal(idx.read("tokens", ids), rows[ids])


def test_corruption_is_a_clear_error(tmp_path):
    d = _token_dir(tmp_path)
    # truncated shard file: error names the file and both byte counts
    victim = os.path.join(d, "shard-00001.tokens.bin")
    with open(victim, "r+b") as f:
        f.truncate(10)
    with pytest.raises(StreamFormatError, match="shard-00001.tokens.bin"):
        load_index(d)
    os.remove(victim)
    with pytest.raises(StreamFormatError, match="missing"):
        load_index(d)

    # unreadable index JSON
    d2 = str(tmp_path / "bad")
    os.makedirs(d2)
    with open(os.path.join(d2, INDEX_BASENAME), "w") as f:
        f.write("{not json")
    with pytest.raises(StreamFormatError, match="unreadable"):
        load_index(d2)

    # no index at all
    with pytest.raises(StreamFormatError, match="no stream index"):
        load_index(str(tmp_path / "nowhere"))

    # wrong kind for the dataset class
    d3 = str(tmp_path / "rec")
    im, lb = synthetic_records(8, image_size=4, num_classes=2, seed=1)
    write_record_shards(d3, (im, lb), image_size=4, num_classes=2,
                        shard_records=4)
    with pytest.raises(StreamFormatError, match="not a token stream"):
        TokenStreamDataset(d3, global_batch_size=4)


def test_stream_smaller_than_global_batch_refused(tmp_path):
    d = _token_dir(tmp_path, n=8)
    with pytest.raises(ValueError, match="8 records < global batch 16"):
        TokenStreamDataset(d, global_batch_size=16)


# ---------------------------------------------------------------------------
# Shuffle: permutation + O(1) seek
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("block", [1, 7, 16, 64, 1000])
def test_block_shuffle_is_a_permutation(block):
    sh = BlockShuffle(64, seed=11, block_size=block)
    seen = []
    for epoch in (0, 1, 2):
        p = sh.epoch_order(epoch).positions(0, 64)
        assert sorted(p) == list(range(64))
        seen.append(tuple(p))
    # epochs reshuffle (vanishingly unlikely to collide for block < n)
    if block < 64:
        assert len(set(seen)) == 3


@pytest.mark.parametrize("block", [1, 7, 16, 1000])
def test_block_shuffle_seek_equals_slice(block):
    sh = BlockShuffle(64, seed=5, block_size=block)
    full = sh.epoch_order(3).positions(0, 64)
    for start, stop in ((0, 64), (20, 50), (63, 64), (10, 10)):
        np.testing.assert_array_equal(
            sh.epoch_order(3).positions(start, stop), full[start:stop]
        )


def test_giant_block_is_one_exact_global_permutation():
    # block >= n: the degenerate case IS a classic full shuffle
    sh = BlockShuffle(40, seed=2, block_size=10_000)
    assert sh.n_blocks == 1
    p = sh.epoch_order(0).positions(0, 40)
    assert sorted(p) == list(range(40)) and list(p) != list(range(40))


# ---------------------------------------------------------------------------
# Dataset: seek bitwise == replay, process-count independence
# ---------------------------------------------------------------------------

def test_epoch_at_bitwise_matches_replayed_stream(tmp_path):
    ds = TokenStreamDataset(
        _token_dir(tmp_path), global_batch_size=16, seed=5, shuffle_block=16
    )
    assert ds.steps_per_epoch == 4 and ds.seq_len == T
    for epoch in (0, 2):
        full = list(ds.epoch(epoch))
        for k in (0, 1, 3, 4):
            tail = list(ds.epoch_at(epoch, k))
            assert len(tail) == len(full) - k
            for (x, y), (rx, ry) in zip(tail, full[k:]):
                np.testing.assert_array_equal(x, rx)
                np.testing.assert_array_equal(y, ry)


def test_global_batch_is_process_count_independent(tmp_path):
    d = _token_dir(tmp_path)
    one = TokenStreamDataset(d, global_batch_size=16, seed=9,
                             shuffle_block=8)
    full = list(one.epoch(1))
    for pc in (2, 4):
        shards = [
            TokenStreamDataset(
                d, global_batch_size=16, seed=9, shuffle_block=8,
                process_index=i, process_count=pc,
            )
            for i in range(pc)
        ]
        iters = [s.epoch(1) for s in shards]
        for x, y in full:
            xs, ys = zip(*[next(it) for it in iters])
            np.testing.assert_array_equal(np.concatenate(xs), x)
            np.testing.assert_array_equal(np.concatenate(ys), y)


def test_record_stream_process_count_independent_and_normalized(tmp_path):
    d = str(tmp_path / "rec")
    im, lb = synthetic_records(48, image_size=4, num_classes=8, seed=3)
    write_record_shards(d, (im, lb), image_size=4, num_classes=8,
                        shard_records=16)
    one = RecordStreamDataset(d, global_batch_size=8, seed=4,
                              image_dtype=np.uint8)
    full = list(one.epoch(0))
    halves = [
        RecordStreamDataset(
            d, global_batch_size=8, seed=4, image_dtype=np.uint8,
            process_index=i, process_count=2,
        )
        for i in range(2)
    ]
    iters = [h.epoch(0) for h in halves]
    for x, y in full:
        xs, ys = zip(*[next(it) for it in iters])
        np.testing.assert_array_equal(np.concatenate(xs), x)
        np.testing.assert_array_equal(np.concatenate(ys), y)
    # float staging normalizes on host (torchvision mean/sd), uint8 is raw
    fl = RecordStreamDataset(d, global_batch_size=8, seed=4,
                             image_dtype=np.float32)
    fx, _ = next(iter(fl.epoch(0)))
    assert fx.dtype == np.float32 and fx.min() < 0  # normalized, not raw

def test_cursor_continues_bitwise_across_process_counts(tmp_path):
    """The elastic-on-real-data oracle: a mid-epoch cursor written at
    world 1 re-enters the stream at world 2 and 4 and the delivered
    GLOBAL batches bitwise-continue the original stream."""
    d = _token_dir(tmp_path)
    one = TokenStreamDataset(d, global_batch_size=16, seed=13,
                             shuffle_block=16)
    full = list(one.epoch(0))
    cur = one.cursor(0, 2)
    assert (cur["epoch"], cur["offset"]) == (0, 2)
    for pc in (2, 4):
        shards = [
            TokenStreamDataset(
                d, global_batch_size=16, seed=cur["seed"], shuffle_block=16,
                process_index=i, process_count=pc,
            )
            for i in range(pc)
        ]
        iters = [s.epoch_at(cur["epoch"], cur["offset"]) for s in shards]
        for x, y in full[2:]:
            xs, ys = zip(*[next(it) for it in iters])
            np.testing.assert_array_equal(np.concatenate(xs), x)
            np.testing.assert_array_equal(np.concatenate(ys), y)


# ---------------------------------------------------------------------------
# Host prefetch: math-neutral, zero host syncs
# ---------------------------------------------------------------------------

def test_host_prefetch_is_math_neutral_and_sync_free(tmp_path):
    from distributeddeeplearning_tpu.utils import hostsync

    ds = TokenStreamDataset(_token_dir(tmp_path), global_batch_size=16,
                            seed=21, shuffle_block=16)
    ref = list(ds.epoch(0))
    before = hostsync.accountant().count
    out = list(host_prefetch(ds.epoch(0), depth=3))
    assert hostsync.accountant().count == before  # zero new host syncs
    assert len(out) == len(ref)
    for (x, y), (rx, ry) in zip(out, ref):
        np.testing.assert_array_equal(x, rx)
        np.testing.assert_array_equal(y, ry)
    # depth<=0 passthrough and early-abandon shutdown both behave
    assert len(list(host_prefetch(ds.epoch(0), depth=0))) == len(ref)
    gen = host_prefetch(ds.epoch(0), depth=2)
    next(gen)
    gen.close()  # must not hang or leak the reader thread


def test_host_prefetch_propagates_reader_errors(tmp_path):
    def boom():
        yield np.zeros((2, 2))
        raise RuntimeError("shard read failed")

    it = host_prefetch(boom(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="shard read failed"):
        list(it)


# ---------------------------------------------------------------------------
# Factory resolution
# ---------------------------------------------------------------------------

def test_make_dataset_resolves_stream(tmp_path):
    from distributeddeeplearning_tpu import data as data_factory
    from distributeddeeplearning_tpu.config import TrainConfig

    d = _token_dir(tmp_path)
    for fmt in ("stream", "auto"):
        cfg = TrainConfig(
            fake=False, data_dir=d, data_format=fmt,
            batch_size_per_device=2, stream_shuffle_block=16,
        )
        ds = data_factory.make_dataset(cfg, train=True)
        assert isinstance(ds, TokenStreamDataset)
        assert ds.global_batch_size == cfg.global_batch_size
        assert ds.shuffle_block == 16
    with pytest.raises(ValueError, match="stream"):
        data_factory.make_dataset(
            TrainConfig(fake=False, data_dir=d, data_format="sideways"),
            train=True,
        )


def test_config_stream_knobs_env_and_validation(tmp_path):
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.training.loop import resolve_engine

    cfg = TrainConfig.from_env(
        {"STREAM_SHUFFLE_BLOCK": "512", "PREFETCH_HOST_BATCHES": "0",
         "DATA_FORMAT": "stream"}
    )
    assert cfg.stream_shuffle_block == 512
    assert cfg.prefetch_host_batches == 0
    assert cfg.data_format == "stream"
    with pytest.raises(ValueError, match="STREAM_SHUFFLE_BLOCK"):
        resolve_engine(TrainConfig(stream_shuffle_block=0))
    with pytest.raises(ValueError, match="PREFETCH_HOST_BATCHES"):
        resolve_engine(TrainConfig(prefetch_host_batches=-1))


# ---------------------------------------------------------------------------
# Training-loop integration: O(1) resume from the manifest cursor
# ---------------------------------------------------------------------------

def _lm_cfg(**kw):
    from distributeddeeplearning_tpu.config import TrainConfig

    base = dict(
        model="lm_tiny", num_classes=VOCAB, batch_size_per_device=2,
        epochs=2, compute_dtype="float32", weight_decay=0.0,
        log_every_steps=0, prefetch_host_batches=2,
    )
    base.update(kw)
    return TrainConfig(**base)


def _lm_fit(cfg, shard_dir, mesh8):
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.training import loop

    data = TokenStreamDataset(
        shard_dir, global_batch_size=cfg.global_batch_size, seed=cfg.seed,
        shuffle_block=cfg.stream_shuffle_block,
    )
    model = get_model("lm_tiny", num_classes=VOCAB, dtype="float32",
                      max_seq_len=T)
    return loop.fit(model, cfg, data, mesh=mesh8, add_default_logger=False)


def _events(obs_dir):
    out = []
    for name in os.listdir(obs_dir):
        if name.startswith("events") and name.endswith(".jsonl"):
            with open(os.path.join(obs_dir, name)) as f:
                for line in f:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
    return out


def test_resume_from_manifest_cursor_is_bitwise_with_zero_replay(
    tmp_path, mesh8, monkeypatch
):
    """The ISSUE acceptance oracle: roll checkpoints back to a MID-epoch
    step and resume — final params bitwise-equal to the uninterrupted
    run, the resume SEEKS (resume_seek point, data.resume_skip_batches
    == 0) and never replays the prefix (no resume_skip point)."""
    from distributeddeeplearning_tpu import faults, obs
    from distributeddeeplearning_tpu.training.checkpoint import (
        CheckpointManager,
    )

    d = _token_dir(tmp_path)
    ref = _lm_fit(_lm_cfg(), d, mesh8)

    ck = str(tmp_path / "ck")
    cfg = _lm_cfg(model_dir=ck, checkpoint_every_steps=3,
                  checkpoint_async=False)
    full = _lm_fit(cfg, d, mesh8)
    for a, b in zip(
        jax.tree.leaves(jax.device_get(ref.state.params)),
        jax.tree.leaves(jax.device_get(full.state.params)),
    ):
        np.testing.assert_array_equal(a, b)

    # "preempt at step 6" (4 steps/epoch -> mid-epoch-1, skip 2)
    steps = faults.checkpoint_steps(ck)
    assert 6 in steps, steps
    for s in steps:
        if s > 6:
            shutil.rmtree(os.path.join(ck, str(s)))

    obs_dir = str(tmp_path / "obs")
    monkeypatch.setenv("OBS_DIR", obs_dir)
    obs.reset()
    try:
        resumed = _lm_fit(cfg, d, mesh8)
        obs.flush()
    finally:
        monkeypatch.delenv("OBS_DIR")
        obs.reset()
    assert resumed.history[0]["epoch_images"] == 32  # 2 of 4 batches left
    for a, b in zip(
        jax.tree.leaves(jax.device_get(ref.state.params)),
        jax.tree.leaves(jax.device_get(resumed.state.params)),
    ):
        np.testing.assert_array_equal(a, b)

    evs = _events(obs_dir)
    points = [e.get("name") for e in evs if e.get("kind") == "point"]
    assert "resume_seek" in points      # the O(1) path ran...
    assert "resume_skip" not in points  # ...and the O(step) replay didn't
    skip_gauges = [
        e["value"] for e in evs
        if e.get("kind") == "gauge"
        and e.get("name") == "data.resume_skip_batches"
    ]
    assert skip_gauges and all(v == 0.0 for v in skip_gauges)
    # data-plane instrumentation flowed through the same stream
    assert any(
        e.get("kind") == "span" and e.get("name") == "data.wait" for e in evs
    )

    # The restored manifest carried the stream cursor (decoded by ANY
    # topology — the identity fields are what loop.fit cross-checks).
    mgr = CheckpointManager(ck, save_every_steps=3)
    template = jax.tree.map(lambda x: x, resumed.state)
    mgr.restore(template, epoch=6)  # the mid-epoch key we resumed from
    cur = (mgr.last_manifest or {}).get("data_cursor")
    assert cur is not None
    assert (cur["epoch"], cur["offset"]) == (1, 2)
    assert cur["records"] == 64 and cur["seed"] == cfg.seed
    # ... and the newest key (end of the resumed run) points at the
    # start of the next epoch.
    mgr.maybe_restore_at(template, steps_per_epoch=4)
    end = (mgr.last_manifest or {}).get("data_cursor")
    mgr.close()
    assert end and (end["epoch"], end["offset"]) == (2, 0)


# ---------------------------------------------------------------------------
# streamgen CLI
# ---------------------------------------------------------------------------

def test_streamgen_cli_corpus_roundtrip(tmp_path, capsys):
    from scripts import streamgen  # repo root on sys.path via conftest

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the quick brown fox jumps over the lazy dog. " * 30)
    out = str(tmp_path / "shards")
    rc = streamgen.main([
        "tokens", "--out", out, "--corpus", str(corpus),
        "--seq-len", "16", "--shard-records", "32",
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["kind"] == "tokens" and summary["records"] > 0

    idx = load_index(out)
    # record 0 is the corpus head: byte-level identity round trip
    raw = corpus.read_bytes()
    np.testing.assert_array_equal(
        idx.read("tokens", np.array([0]))[0],
        np.frombuffer(raw[:17], np.uint8).astype(np.int32),
    )
    ds = open_stream_dataset(out, global_batch_size=8)
    assert isinstance(ds, TokenStreamDataset) and ds.vocab_size == 256

    rows = corpus_to_rows(b"0123456789", seq_len=4, stride=2)
    assert rows.shape == (3, 5)
    with pytest.raises(ValueError, match="too short"):
        corpus_to_rows(b"abc", seq_len=16)


# ---------------------------------------------------------------------------
# Pretrain -> checkpoint -> serve (the lm_stream pipeline, compact)
# ---------------------------------------------------------------------------

def test_served_tokens_match_generate_after_restore(tmp_path, mesh8):
    """The pretrain→serve oracle behind the lm_stream recertify row: a
    SlotEngine loaded with the RESTORED-from-disk params serves greedy
    continuations token-equal to ``inference.generate`` on the same
    params."""
    from distributeddeeplearning_tpu.inference import generate
    from distributeddeeplearning_tpu.models import get_model
    from distributeddeeplearning_tpu.serving import SlotEngine
    from distributeddeeplearning_tpu.training import loop
    from distributeddeeplearning_tpu.training.checkpoint import (
        CheckpointManager,
    )

    d = _token_dir(tmp_path, n=32)
    ck = str(tmp_path / "ck")
    cfg = _lm_cfg(epochs=1, model_dir=ck, checkpoint_every_steps=2,
                  checkpoint_async=False)
    data = TokenStreamDataset(
        d, global_batch_size=cfg.global_batch_size, seed=cfg.seed,
        shuffle_block=cfg.stream_shuffle_block,
    )
    model = get_model("lm_tiny", num_classes=VOCAB, dtype="float32",
                      max_seq_len=T + 6)
    trained = loop.fit(model, cfg, data, mesh=mesh8,
                       add_default_logger=False)

    mgr = CheckpointManager(ck, save_every_steps=2)
    restored = mgr.restore(
        jax.tree.map(lambda x: jax.numpy.zeros_like(x), trained.state)
    )
    assert (mgr.last_manifest or {}).get("data_cursor") is not None
    mgr.close()
    for a, b in zip(
        jax.tree.leaves(jax.device_get(trained.state.params)),
        jax.tree.leaves(jax.device_get(restored.params)),
    ):
        np.testing.assert_array_equal(a, b)

    prompts = data.index.read("tokens", np.arange(2))[:, :4].astype(np.int32)
    engine = SlotEngine(model, restored.params, num_slots=2, max_len=T + 6)
    served = np.asarray(
        generate(model, restored.params, prompts, max_new_tokens=4,
                 engine=engine)
    )
    reference = np.asarray(
        generate(model, restored.params, jax.numpy.asarray(prompts),
                 max_new_tokens=4)
    )
    np.testing.assert_array_equal(served, reference)
