"""Native IO tier tests (native/ddl_native.cc + ctypes bindings).

The C++ path and the pure-Python fallback must be byte-identical, and
both must interoperate with TensorFlow's own TFRecord/Example readers —
the compatibility contract that lets the framework's writer feed the
tf.data pipeline (``data/imagenet.py``).
"""

import numpy as np
import pytest

import distributeddeeplearning_tpu.native as native
from distributeddeeplearning_tpu.native import (
    count_records,
    crc32c,
    fill_uniform,
    index_tfrecord,
    masked_crc32c,
    read_tfrecord,
    write_tfrecord,
)
from distributeddeeplearning_tpu.native.example_proto import (
    encode_example,
    parse_example,
)

PAYLOADS = [b"hello tfrecord", b"", b"x" * 1000, bytes(range(256))]


def test_crc32c_known_answer():
    # RFC 3720 check value for CRC-32C
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    assert native._crc32c_py(b"123456789") == 0xE3069283


def test_native_library_builds():
    """g++ is in the image (SURVEY/environment contract) — the native
    build must actually succeed here, not silently fall back."""
    assert native.native_available(), "libddl_native.so failed to build"


def test_python_fallback_matches_native(tmp_path, monkeypatch):
    if not native.native_available():
        pytest.skip("no native lib to compare against")
    native_file = tmp_path / "native.tfrecord"
    write_tfrecord(str(native_file), PAYLOADS)
    # force the pure-Python path
    monkeypatch.setattr(native, "load_library", lambda: None)
    py_file = tmp_path / "py.tfrecord"
    write_tfrecord(str(py_file), PAYLOADS)
    assert native_file.read_bytes() == py_file.read_bytes()
    assert crc32c(b"123456789") == 0xE3069283  # fallback crc
    offs, lens = index_tfrecord(str(native_file))  # fallback indexer
    assert list(lens) == [len(p) for p in PAYLOADS]
    assert read_tfrecord(str(py_file)) == PAYLOADS


def test_roundtrip_and_index(tmp_path):
    path = tmp_path / "a.tfrecord"
    write_tfrecord(str(path), PAYLOADS)
    assert read_tfrecord(str(path)) == PAYLOADS
    assert count_records(str(path)) == len(PAYLOADS)
    offsets, lengths = index_tfrecord(str(path))
    assert list(lengths) == [len(p) for p in PAYLOADS]
    # offsets point at the payloads themselves
    blob = path.read_bytes()
    for payload, off, length in zip(PAYLOADS, offsets, lengths):
        assert blob[int(off) : int(off) + int(length)] == payload
    # append mode
    write_tfrecord(str(path), [b"tail"], append=True)
    assert read_tfrecord(str(path))[-1] == b"tail"


def test_corruption_detected(tmp_path):
    path = tmp_path / "bad.tfrecord"
    write_tfrecord(str(path), PAYLOADS)
    blob = bytearray(path.read_bytes())
    blob[14] ^= 0xFF  # flip a payload byte of record 0
    path.write_bytes(bytes(blob))
    with pytest.raises(IOError):
        index_tfrecord(str(path), verify=True)
    # verify=False skips CRCs and still walks the framing
    assert count_records(str(path), verify=False) == len(PAYLOADS)


def test_tf_reads_native_file(tmp_path):
    tf = pytest.importorskip("tensorflow")
    path = tmp_path / "native.tfrecord"
    write_tfrecord(str(path), PAYLOADS)
    got = [bytes(r.numpy()) for r in tf.data.TFRecordDataset(str(path))]
    assert got == PAYLOADS


def test_native_reads_tf_file(tmp_path):
    tf = pytest.importorskip("tensorflow")
    path = tmp_path / "tf.tfrecord"
    with tf.io.TFRecordWriter(str(path)) as w:
        for p in PAYLOADS:
            w.write(p)
    assert read_tfrecord(str(path), verify=True) == PAYLOADS


def test_example_codec_roundtrip():
    ex = {"image/encoded": b"\x89JPGDATA", "image/class/label": [417]}
    payload = encode_example(ex)
    assert parse_example(payload) == ex


def test_example_codec_vs_tensorflow():
    tf = pytest.importorskip("tensorflow")
    payload = encode_example(
        {"image/encoded": b"jpegbytes", "image/class/label": [7]}
    )
    feats = tf.io.parse_single_example(
        payload,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
        },
    )
    assert bytes(feats["image/encoded"].numpy()) == b"jpegbytes"
    assert int(feats["image/class/label"].numpy()) == 7
    # and the inverse: parse TF's own serialization
    ex = tf.train.Example(
        features=tf.train.Features(
            feature={
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[b"abc"])
                ),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[99])
                ),
            }
        )
    )
    parsed = parse_example(ex.SerializeToString())
    assert parsed["image/encoded"] == b"abc"
    assert parsed["image/class/label"] == [99]


def test_fill_uniform_deterministic(monkeypatch):
    a = fill_uniform((64, 7), seed=123, n_threads=1)
    b = fill_uniform((64, 7), seed=123, n_threads=4)
    np.testing.assert_array_equal(a, b)  # thread-count invariant
    assert a.shape == (64, 7) and a.dtype == np.float32
    assert 0.0 <= a.min() and a.max() < 1.0
    c = fill_uniform((64, 7), seed=124, n_threads=1)
    assert np.abs(a - c).max() > 0
    # numpy fallback is bit-identical to the C++ path
    monkeypatch.setattr(native, "load_library", lambda: None)
    d = fill_uniform((64, 7), seed=123)
    np.testing.assert_array_equal(a, d)
