"""KV-cache generation tests (inference.generate + Attention decode).

Oracle: incremental decoding with the cache must produce exactly the
same tokens as re-running the full forward pass over the growing
sequence — any off-by-one in the cache index, position embedding
counter, or decode mask breaks the equality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.inference import generate
from distributeddeeplearning_tpu.models.transformer_lm import TransformerLM

VOCAB, MAX_LEN = 64, 32


def _model(**kw):
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32, **kw,
    )


def _params(model, seed=0):
    import flax.linen as nn

    variables = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


def _greedy_reference(model, params, prompt, n_new):
    """Token-by-token greedy via full re-forward (no cache)."""
    seq = jnp.asarray(prompt)
    for _ in range(n_new):
        logits = model.apply({"params": params}, seq, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return np.asarray(seq)


def test_greedy_cache_matches_full_forward():
    model = _model()
    params = _params(model)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, VOCAB, size=(2, 5)).astype(np.int32)
    got = np.asarray(generate(model, params, prompt, max_new_tokens=8))
    ref = _greedy_reference(model, params, prompt, 8)
    np.testing.assert_array_equal(got, ref)
    assert got.shape == (2, 13)
    np.testing.assert_array_equal(got[:, :5], prompt)  # prompt preserved


def test_greedy_cache_matches_full_forward_moe():
    """Decode runs the MoE mixture without capacity dropping (chunk-
    length-dependent drops can't be cache-consistent), so the oracle is
    the no-drop full forward: capacity_factor = num_experts."""
    model = _model(moe_experts=4, moe_capacity_factor=0.5)  # drops in train
    params = _params(model, seed=1)
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, VOCAB, size=(1, 4)).astype(np.int32)
    got = np.asarray(generate(model, params, prompt, max_new_tokens=6))
    no_drop = _model(moe_experts=4, moe_capacity_factor=4.0)
    ref = _greedy_reference(no_drop, params, prompt, 6)
    np.testing.assert_array_equal(got, ref)


def test_sampling_deterministic_per_seed():
    model = _model()
    params = _params(model)
    prompt = np.zeros((2, 3), np.int32)
    a = np.asarray(generate(model, params, prompt, max_new_tokens=10,
                            temperature=1.0, top_k=8,
                            rng=jax.random.PRNGKey(7)))
    b = np.asarray(generate(model, params, prompt, max_new_tokens=10,
                            temperature=1.0, top_k=8,
                            rng=jax.random.PRNGKey(7)))
    c = np.asarray(generate(model, params, prompt, max_new_tokens=10,
                            temperature=1.0, top_k=8,
                            rng=jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert a.max() < VOCAB and a.min() >= 0


def test_length_guard():
    model = _model()
    params = _params(model)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, np.zeros((1, 30), np.int32),
                 max_new_tokens=10)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(model, params, np.zeros((1, 4), np.int32),
                 max_new_tokens=0)


def test_single_new_token():
    model = _model()
    params = _params(model)
    prompt = np.ones((1, 4), np.int32)
    got = np.asarray(generate(model, params, prompt, max_new_tokens=1))
    ref = _greedy_reference(model, params, prompt, 1)
    np.testing.assert_array_equal(got, ref)


def test_top_p_sampling():
    """Nucleus filter: with a peaked distribution and small p, sampling
    can only return the top token; the filter composes with top_k."""
    from distributeddeeplearning_tpu.inference import _sample

    logits = jnp.asarray(
        [[10.0, 5.0, 1.0, 0.0], [0.0, 10.0, 9.9, 1.0]], jnp.float32
    )
    # p small enough that only the argmax survives in row 0; row 1's top
    # two are near-equal so p=0.9 keeps both
    for _ in range(8):
        tok = _sample(logits, jax.random.PRNGKey(_), 1.0, None, 0.5)
        assert int(tok[0]) == 0
    seen = {
        int(_sample(logits, jax.random.PRNGKey(s), 1.0, None, 0.9)[1])
        for s in range(32)
    }
    assert seen <= {1, 2}
    assert len(seen) == 2  # both nucleus members actually get sampled
    # end-to-end through generate()
    model = _model()
    params = _params(model)
    out = generate(model, params, np.zeros((1, 3), np.int32),
                   max_new_tokens=5, temperature=1.0, top_p=0.8,
                   rng=jax.random.PRNGKey(0))
    assert np.asarray(out).shape == (1, 8)


def test_top_p_validation_and_dp_rules_allowed():
    model = _model()
    params = _params(model)
    with pytest.raises(ValueError, match="top_p"):
        generate(model, params, np.zeros((1, 3), np.int32),
                 max_new_tokens=2, temperature=1.0, top_p=0.0)
    # PARAM_SHARDING=dp under the dp engine is valid (replicated params)
    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.training.loop import resolve_engine

    engine, _ = resolve_engine(TrainConfig(engine="dp", param_sharding="dp"))
    assert engine == "dp"


def test_top_k_validated():
    model = _model()
    params = _params(model)
    prompt = np.zeros((1, 3), np.int32)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, max_new_tokens=2,
                 temperature=1.0, top_k=0)
    # top_k > vocab is clamped (keeps everything), not an IndexError
    out = np.asarray(generate(model, params, prompt, max_new_tokens=2,
                              temperature=1.0, top_k=VOCAB + 100,
                              rng=jax.random.PRNGKey(3)))
    assert out.shape == (1, 5)


def test_eos_freezes_finished_rows():
    """After a row emits eos_token, its remaining positions are pad."""
    model = _model()
    params = _params(model)
    prompt = np.asarray([[1, 2, 3]], np.int32)
    ref = np.asarray(generate(model, params, prompt, max_new_tokens=10))
    # pick the token the greedy path actually emits early, use it as eos
    eos = int(ref[0, 4])  # second generated token
    got = np.asarray(
        generate(model, params, prompt, max_new_tokens=10,
                 eos_token=eos, pad_token=0)
    )
    # identical up to and including the FIRST eos occurrence (the chosen
    # token may already appear earlier in the greedy stream — freezing
    # from that earlier point is the correct behaviour), pad afterwards
    gen = ref[0, prompt.shape[1]:]
    first = prompt.shape[1] + int(np.argmax(gen == eos))
    np.testing.assert_array_equal(got[0, : first + 1], ref[0, : first + 1])
    assert got[0, first] == eos
    np.testing.assert_array_equal(got[0, first + 1:], 0)


def test_tp_sharded_state_decodes_token_identically(devices):
    """VERDICT r2 #7: decode straight from a TP-sharded (ENGINE=pjit)
    state on the 8-device mesh — no host gather, no replication — and
    get exactly the replicated path's tokens."""
    import optax

    from distributeddeeplearning_tpu.config import TrainConfig
    from distributeddeeplearning_tpu.parallel.mesh import create_mesh
    from distributeddeeplearning_tpu.training.pjit_step import build_pjit_state

    model = _model()
    mesh = create_mesh(axes=("data", "model"), shape=(2, 4))
    cfg = TrainConfig(engine="pjit", num_classes=VOCAB,
                      compute_dtype="float32", seed=7)
    state = build_pjit_state(
        model, cfg, optax.sgd(0.1), mesh,
        input_shape=(1, MAX_LEN), input_dtype=jnp.int32,
    )
    qkv = state.params["block0"]["attn"]["qkv"]["kernel"]
    assert "model" in tuple(qkv.sharding.spec)  # genuinely sharded

    rng = np.random.RandomState(0)
    prompt = rng.randint(0, VOCAB, size=(2, 5)).astype(np.int32)
    sharded_out = np.asarray(
        generate(model, state.params, prompt, max_new_tokens=8)
    )
    host_params = jax.device_get(state.params)
    ref_out = np.asarray(
        generate(model, host_params, prompt, max_new_tokens=8)
    )
    np.testing.assert_array_equal(sharded_out, ref_out)


def test_cache_buffers_sized_to_request_not_max_seq_len():
    """Round 5: KV buffers are allocated at prompt+max_new_tokens, not
    model.max_seq_len — decode streams the whole static buffer every
    step, so buffer length IS the KV byte cost (scripts/decode_audit.py).
    Shape-only check via the same eval_shape the sampler uses."""
    model = _model()  # max_seq_len = 32
    decode_model = model.clone(decode=True, attn_impl="xla", seq_axis=None)
    b, total = 2, 12  # 5-token prompt + 7 new << max_seq_len
    shapes = jax.eval_shape(
        lambda r: decode_model.init(
            r, jnp.zeros((b, total), jnp.int32), train=False
        ),
        jax.random.PRNGKey(0),
    )["cache"]
    lengths = {
        leaf.shape[1] for leaf in jax.tree.leaves(shapes) if leaf.ndim >= 3
    }
    assert lengths == {total}, lengths
    # and generation at that size still matches the full re-forward
    params = _params(model)
    prompt = np.random.RandomState(5).randint(
        0, VOCAB, size=(b, 5)
    ).astype(np.int32)
    got = np.asarray(generate(model, params, prompt, max_new_tokens=7))
    np.testing.assert_array_equal(got, _greedy_reference(model, params, prompt, 7))


def test_topk_fast_path_matches_sort_reference():
    """Round 5: the top-k-only sampler uses lax.top_k instead of a full
    vocab sort — the filtered distribution (and hence the draw, same
    key) must be identical to the sort-based construction."""
    from distributeddeeplearning_tpu.inference import _sample

    rng = np.random.RandomState(7)
    logits = jnp.asarray(rng.randn(3, 101).astype(np.float32) * 4)
    key = jax.random.PRNGKey(9)
    for k in (1, 5, 40, 101, 500):
        got = _sample(logits, key, temperature=0.7, top_k=k)
        scaled = logits / 0.7
        srt = jnp.sort(scaled, axis=-1)[:, ::-1]
        kth = srt[:, min(k, scaled.shape[-1]) - 1][:, None]
        ref_logits = jnp.where(
            scaled < kth, jnp.finfo(jnp.float32).min, scaled
        )
        ref = jax.random.categorical(key, ref_logits, axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
