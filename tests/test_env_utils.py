"""Infra-utils parity tests (reference ``common/utils.py:12-31``)."""

import json
import os

from distributeddeeplearning_tpu.utils.env import (
    dotenv_for,
    export_env_file,
    get_secret,
    load_env_file,
    set_key,
    write_json_to_file,
)


def test_dotenv_roundtrip(tmp_path):
    path = str(tmp_path / ".env")
    assert dotenv_for(path) == path and os.path.exists(path)
    set_key(path, "PROJECT", "my-proj")
    set_key(path, "ZONE", "us-west4-a")
    set_key(path, "PROJECT", "other")  # overwrite in place
    vals = load_env_file(path)
    assert vals == {"PROJECT": "other", "ZONE": "us-west4-a"}


def test_load_skips_comments_and_quotes(tmp_path):
    path = tmp_path / ".env"
    path.write_text("# comment\n\nA='quoted'\nB=\"dq\"\nnoequals\n")
    assert load_env_file(str(path)) == {"A": "quoted", "B": "dq"}


def test_export_env_file(tmp_path):
    path = tmp_path / ".env"
    path.write_text("DDL_TEST_KEY=val\n")
    env = {}
    export_env_file(str(path), env)
    assert env["DDL_TEST_KEY"] == "val"
    env2 = {"DDL_TEST_KEY": "keep"}
    export_env_file(str(path), env2)  # existing wins (setdefault)
    assert env2["DDL_TEST_KEY"] == "keep"


def test_get_secret_prompts_once(tmp_path, monkeypatch):
    path = str(tmp_path / ".env")
    calls = []

    def fake_getpass(prompt):
        calls.append(prompt)
        return "s3cret"

    monkeypatch.setattr("getpass.getpass", fake_getpass)
    assert get_secret("TOKEN", path) == "s3cret"
    assert get_secret("TOKEN", path) == "s3cret"  # from file, no reprompt
    assert len(calls) == 1


def test_write_json_to_file(tmp_path):
    out = tmp_path / "job.json"
    write_json_to_file({"b": 1, "a": [1, 2]}, str(out))
    assert json.loads(out.read_text()) == {"a": [1, 2], "b": 1}


def test_docker_login_from_env_file(tmp_path):
    """make push auth (VERDICT r3 #8): credentials come from .env alone —
    a clean shell with only the env file must produce a docker login
    call with the password on stdin, never in argv."""
    from distributeddeeplearning_tpu.utils.env import docker_login, set_key

    path = str(tmp_path / ".env")
    set_key(path, "DOCKER_USER", "alice")
    set_key(path, "DOCKER_PASSWORD", "s3cret")
    calls = {}

    class Result:
        returncode = 0

    def runner(cmd, input=None):
        calls["cmd"] = cmd
        calls["stdin"] = input
        return Result()

    assert docker_login(path, runner=runner) == 0
    assert calls["cmd"] == [
        "docker", "login", "--username", "alice", "--password-stdin"
    ]
    assert calls["stdin"] == b"s3cret"
    assert "s3cret" not in " ".join(calls["cmd"])

    # a REGISTRY key routes the login to that registry
    set_key(path, "REGISTRY", "gcr.io")
    docker_login(path, runner=runner)
    assert calls["cmd"][-1] == "gcr.io"


def test_docker_login_noninteractive_without_creds_skips(tmp_path, capsys):
    """CI contract: an already-authenticated daemon + no .env credentials
    must not die in getpass — login no-ops so `make push` proceeds."""
    from distributeddeeplearning_tpu.utils.env import docker_login

    called = {}

    def runner(cmd, input=None):  # pragma: no cover - must not run
        called["cmd"] = cmd

    # pytest's captured stdin is not a tty
    assert docker_login(str(tmp_path / ".env"), runner=runner) == 0
    assert not called
