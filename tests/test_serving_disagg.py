"""Disaggregated prefill/decode serving oracles (docs/SERVING.md).

The disaggregation tier's claims, each pinned here:

1. **Handoff bitwise parity** — a prefill-pool replica prefills, the
   router hands the exported block table to a decode-pool replica, and
   the delivered stream is bitwise the sequential ``generate``
   reference; prefill programs never run on decode replicas and every
   engine's program set stays closed.
2. **Fleet-wide prefix directory** — a greedy export publishes its
   prompt; an identical later prompt is ADOPTED (state transplant,
   zero additional prefill-program executions anywhere in the fleet),
   and every ``(rid, bid)`` the directory maps is pinned + resident on
   that replica (the LRU can never evict a directory-mapped block).
3. **Live KV-block migration** — ``Router.migrate`` moves a running
   stream between decode replicas as a state transplant: zero drops,
   bitwise splice, ``serve.migrations`` accounted.
4. **Ledger balance under churn** — cancel-mid-handoff and a prefill
   replica dying mid-handoff leak nothing: after the storm drains and
   the directory releases its pins, every live allocator is back to
   ``live_count == 0`` and ``free_count == capacity``.
5. **Per-pool autoscale** — ``ControllerConfig.pools`` scales the hot
   pool with ``factory(rid, pool)`` and drains the cold one without
   touching its sibling.

Engines are tiny (64-vocab lm) and replicas are pumped inline
(threaded=False): every step of every pump happens on the test thread.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from distributeddeeplearning_tpu.inference import generate  # noqa: E402
from distributeddeeplearning_tpu.models.transformer_lm import (  # noqa: E402
    TransformerLM,
)
from distributeddeeplearning_tpu.serving import (  # noqa: E402
    BlockAllocator,
    BlockPoolExhausted,
    ControllerConfig,
    FleetConfig,
    FleetController,
    PrefixDirectory,
    Replica,
    Request,
    Router,
    ServeConfig,
)
from distributeddeeplearning_tpu.serving.fleet import (  # noqa: E402
    PoolWatermarks,
)

VOCAB, MAX_LEN, BLOCK = 64, 32, 4


@pytest.fixture(scope="module")
def model():
    return TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=MAX_LEN,
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def params(model):
    import flax.linen as nn

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, MAX_LEN), jnp.int32),
        train=False,
    )
    return nn.unbox(variables["params"])


def _scfg(**over):
    kw = dict(
        num_slots=2, buckets=(8,), prefills_per_step=2,
        kv_layout="paged", block_size=BLOCK,
    )
    kw.update(over)
    return ServeConfig(**kw)


def _prompt(rng, n=8):
    return rng.randint(0, VOCAB, size=(n,)).astype(np.int32)


def _ref_new(model, params, prompt, max_new):
    """Greedy reference NEW tokens for ``prompt`` (the oracle every
    disagg path must match bitwise)."""
    out = np.asarray(generate(
        model, params, np.asarray(prompt)[None],
        max_new_tokens=max_new, temperature=0.0,
    ))[0]
    return [int(t) for t in out[len(prompt):]]


def _pump(router, until, limit=6000):
    """Step the router until ``until()`` or idle; bounded."""
    for _ in range(limit):
        if until():
            return True
        if not router.step():
            break
    return until()


def _ledger_balanced(replica):
    """Allocator back to rest: nothing referenced, everything
    allocatable (free list + unpinned evictable cache)."""
    a = replica.engine.allocator
    return a.live_count == 0 and a.free_count == a.capacity


def _release_directory(router):
    """Teardown half of the directory contract: drop every entry and
    unpin the returned mappings on their (live) replicas."""
    if router.directory is None:
        return
    by_rid = {r.rid: r for r in router.replicas}
    for rid, bids in router.directory.clear():
        r = by_rid.get(rid)
        if r is None or r.engine is None or r.engine.allocator is None:
            continue
        for bid in bids:
            r.engine.allocator.unpin(bid)


# -- directory unit oracles (pure host, no engine) -----------------------


def _payload(n_blocks, fill=0.5):
    return {("layer", "k"): np.full(
        (n_blocks, BLOCK, 2), fill, np.float32
    )}


def test_directory_publish_lookup_adopt():
    d = PrefixDirectory()
    p = np.arange(8, dtype=np.int32)
    assert d.lookup(p) is None and d.stats["hits"] == 0
    assert d.publish(
        0, p, [3, 7], _payload(2), first_token=5, block_size=BLOCK
    )
    # Same holder republishing is a no-op (caller unpins); a second
    # replica becomes an additional holder of the same entry.
    assert not d.publish(
        0, p, [3, 7], _payload(2), first_token=5, block_size=BLOCK
    )
    assert d.publish(
        1, p, [9], _payload(1), first_token=5, block_size=BLOCK
    )
    ent = d.lookup(p)
    assert ent is not None and ent["owner"] == 0
    assert ent["holders"] == {0: [3, 7], 1: [9]}
    assert ent["first_token"] == 5 and ent["adoptions"] == 0
    assert d.adopt(p)["adoptions"] == 1
    assert len(d) == 1
    assert d.stats["lookups"] == 3 and d.stats["hits"] == 2
    assert sorted(d.mapped_blocks(0)) == [3, 7]


def test_directory_chain_lookup_and_drop_replica():
    d = PrefixDirectory()
    p = np.arange(8, dtype=np.int32)
    d.publish(0, p, [3, 7], _payload(2), first_token=5, block_size=BLOCK)
    # A longer prompt sharing the first full block chain-hits; the
    # payload slice covers exactly the matched rows.
    longer = np.concatenate([p[:4], np.full(4, 63, np.int32)])
    n, ent, sliced = d.lookup_chain(longer, BLOCK)
    assert n == 1 and ent is not None
    assert sliced[("layer", "k")].shape[0] == 1
    # Block-size mismatch is a miss, never a wrong-shaped hit.
    assert d.lookup_chain(p, BLOCK * 2) == (0, None, {})
    # Owner death re-homes to a surviving holder ...
    d.publish(1, p, [9], _payload(1), first_token=5, block_size=BLOCK)
    unmapped = d.drop_replica(0)
    assert unmapped == [(0, [3, 7])]
    assert d.lookup(p)["owner"] == 1 and d.stats["rehomed"] == 1
    # ... and the last holder's death drops the entry and its chains.
    d.drop_replica(1)
    assert len(d) == 0 and d.lookup(p) is None
    assert d.lookup_chain(longer, BLOCK) == (0, None, {})
    assert d.stats["dropped"] == 1


def test_directory_clear_returns_every_mapping():
    d = PrefixDirectory()
    a = np.arange(8, dtype=np.int32)
    b = np.arange(8, 16, dtype=np.int32)
    d.publish(0, a, [1, 2], _payload(2), first_token=0, block_size=BLOCK)
    d.publish(1, b, [4], _payload(1), first_token=0, block_size=BLOCK)
    got = sorted(d.clear())
    assert got == [(0, [1, 2]), (1, [4])]
    assert len(d) == 0 and d.lookup(a) is None


def test_allocator_pins_block_eviction_and_recycling():
    a = BlockAllocator(num_blocks=6, block_size=BLOCK)  # 5 usable
    bids = a.alloc(2)
    with pytest.raises(KeyError):
        a.pin(999)  # not resident anywhere
    a.pin(bids[0])
    for bid in bids:
        a.decref(bid)
    # The pinned (unregistered) block stays resident instead of
    # returning to the free list, and is excluded from free capacity.
    assert a.pinned(bids[0]) and a.free_count == a.capacity - 1
    with pytest.raises(BlockPoolExhausted):
        a.alloc(a.capacity)
    # A pinned *registered* block survives eviction pressure: filling
    # the pool evicts every other cached block but never the pin.
    toks = np.arange(BLOCK, dtype=np.int32)
    reg = a.alloc(1)
    a.register_prefix(toks, reg)
    a.pin(reg[0])
    a.decref(reg[0])
    grab = a.alloc(a.free_count)
    assert a.pinned(reg[0]) and a.peek_prefix(toks, BLOCK) == 1
    for bid in grab:
        a.decref(bid)
    # Unpin releases both: the registered block becomes evictable, the
    # unregistered one returns to the free list; ledger balances.
    a.unpin(bids[0])
    a.unpin(reg[0])
    assert a.live_count == 0 and a.free_count == a.capacity


def test_prefix_reuse_never_windows_past_position_capacity():
    """A cached-prefix hit shifts the suffix prefill's bucket window to
    [start, start + bucket); past the position-embedding capacity the
    padded tail's rows gather as NaN fill, the NaN K/V lands in the
    trash block, and zero-weight × NaN poisons EVERY slot's attention
    (the disagg bench's 96-token prompt over a 32-token hot prefix
    found this — all-zero argmax streams). The engine must shrink the
    match until the window fits — bitwise parity over reuse depth."""
    import flax.linen as nn

    cap = 10  # == engine max_len: bucket windows past 10 have no rows
    m = TransformerLM(
        variant="tiny", vocab_size=VOCAB, max_seq_len=cap,
        dtype=jnp.float32,
    )
    p10 = nn.unbox(m.init(
        jax.random.PRNGKey(2), jnp.zeros((2, cap), jnp.int32),
        train=False,
    )["params"])
    router = Router(config=FleetConfig(replicas=1))
    # An 8-token prompt with a 1-block hit would window [4, 12) in the
    # (8,) bucket — two rows past capacity — unless the match shrinks.
    router.add_replica(
        Replica(0, m, p10, _scfg(), max_len=cap, pool="mixed"),
        start=True, threaded=False,
    )
    _pump(router, lambda: all(r.state == "ready" for r in router.replicas))
    rng = np.random.RandomState(23)
    a = _prompt(rng, 8)
    b = np.concatenate([a[:BLOCK], _prompt(rng, 4)]).astype(np.int32)
    try:
        for p in (a, b):
            fh = router.submit(Request(
                prompt=p, max_new_tokens=2, temperature=0.0,
            ))
            assert _pump(router, lambda: fh.done.is_set())
            assert [int(t) for t in fh.new_tokens] == _ref_new(
                m, p10, p, 2
            )
    finally:
        router.close()


# -- fleet config --------------------------------------------------------


def test_fleet_config_disagg_env_and_pool_split():
    cfg = FleetConfig.from_env({
        "SERVE_REPLICAS": "4", "SERVE_DISAGG": "1",
        "SERVE_POOL_PREFILL": "1", "SERVE_DISAGG_DIRECTORY": "0",
    })
    assert cfg.disagg and not cfg.directory
    assert cfg.pool_split() == (1, 3)
    assert FleetConfig(replicas=4, disagg=True).pool_split() == (2, 2)
    assert FleetConfig(replicas=5, disagg=True).pool_split() == (2, 3)
    assert FleetConfig(
        replicas=5, disagg=True, decode_pool=4
    ).pool_split() == (1, 4)
    # Colocated fleets have no pools at all.
    assert FleetConfig(replicas=4).pool_split() == (0, 0)
    with pytest.raises(ValueError):
        FleetConfig(replicas=1, disagg=True).validate()
    with pytest.raises(ValueError):
        FleetConfig(replicas=3, disagg=True, prefill_pool=3).validate()


# -- disaggregated fleet (1 prefill + 2 decode, inline) ------------------


@pytest.fixture(scope="module")
def dfleet(model, params):
    """One long-lived disaggregated fleet shared by the non-destructive
    tests below (engine compiles amortized module-wide). The directory
    is the router's, so entries accumulate across tests — each test
    uses fresh prompts unless reuse is the point."""
    pools = ("prefill", "decode", "decode")
    reps = [
        Replica(
            k, model, params, _scfg(), max_len=MAX_LEN, pool=pools[k]
        ).start(threaded=False)
        for k in range(3)
    ]
    router = Router(config=FleetConfig(
        replicas=3, disagg=True, prefill_pool=1, decode_pool=2,
    ))
    for r in reps:
        router.add_replica(r, start=False)
    assert router.directory is not None
    yield router
    router.close()


def test_handoff_bitwise_parity_and_closed_pools(dfleet, model, params):
    rng = np.random.RandomState(7)
    cases = []
    for i in range(6):
        p = _prompt(rng, n=4 + (i % 5))
        cases.append((p, 4 + (i % 4), dfleet.submit(Request(
            prompt=p, max_new_tokens=4 + (i % 4), temperature=0.0,
        ))))
    handles = [fh for _, _, fh in cases]
    assert _pump(dfleet, lambda: all(h.done.is_set() for h in handles))
    for p, n, fh in cases:
        assert fh.finish_reason in ("eos", "length")
        assert fh.new_tokens == _ref_new(model, params, p, n)[
            : len(fh.new_tokens)
        ]
        assert fh.restart_consistent
        # Decode happened on the decode pool, not where prefill ran.
        assert dfleet._replica(fh.replica_id).pool == "decode"
    assert dfleet.stats["handoffs"] >= 6
    pre, dec = dfleet._replica(0), dfleet.replicas[1:]
    assert pre.pool == "prefill" and pre.engine.prefill_execs >= 6
    for r in dec:
        # Prefill-once is structural: decode replicas run NO prefill
        # programs, ever — work arrives only as imported block tables.
        assert r.engine.prefill_execs == 0
    for r in dfleet.replicas:
        assert r.engine.compile_count == r.engine.programs_expected, (
            f"replica {r.rid} ({r.pool}) program set not closed"
        )


def test_directory_adoption_runs_zero_prefill(dfleet, model, params):
    rng = np.random.RandomState(11)
    hot = _prompt(rng, n=8)  # two full blocks: publishable + pinnable
    first = dfleet.submit(Request(
        prompt=hot, max_new_tokens=6, temperature=0.0,
    ))
    assert _pump(dfleet, first.done.is_set)
    assert first.new_tokens == _ref_new(model, params, hot, 6)
    assert dfleet.directory.lookup(hot.copy()) is not None
    execs_pre = sum(r.engine.prefill_execs for r in dfleet.replicas)
    hits_pre = dfleet.stats["directory_hits"]
    second = dfleet.submit(Request(
        prompt=hot, max_new_tokens=6, temperature=0.0,
    ))
    assert _pump(dfleet, second.done.is_set)
    assert second.new_tokens == first.new_tokens
    assert sum(
        r.engine.prefill_execs for r in dfleet.replicas
    ) == execs_pre, "adoption must not run any prefill program"
    assert dfleet.stats["directory_hits"] > hits_pre
    assert dfleet.directory.lookup(hot)["adoptions"] >= 1


def test_directory_mapped_blocks_are_pinned_and_resident(dfleet):
    mapped_total = 0
    for r in dfleet.replicas:
        a = r.engine.allocator
        for bid in dfleet.directory.mapped_blocks(r.rid):
            mapped_total += 1
            assert a.pinned(bid), f"mapped block {bid} unpinned on {r.rid}"
            assert bid in a._ref or bid in a._lru, (
                f"mapped block {bid} not resident on {r.rid}"
            )
    assert mapped_total >= 1, "no publish pinned anything"


def test_live_migration_zero_drop_bitwise(dfleet, model, params):
    rng = np.random.RandomState(13)
    p = _prompt(rng, n=6)
    fh = dfleet.submit(Request(
        prompt=p, max_new_tokens=12, temperature=0.0,
    ))
    assert _pump(dfleet, lambda: (
        len(fh.new_tokens) >= 3 and fh.status == "running"
        and fh.replica_id is not None
        and dfleet._replica(fh.replica_id).pool == "decode"
    ))
    src = fh.replica_id
    migs_pre = dfleet.stats["migrations"]
    moved = dfleet.migrate(src)
    assert moved == 1, "sibling decode replica had room: expected transplant"
    assert dfleet.stats["migrations"] == migs_pre + 1
    assert fh.replica_id != src and fh.status == "running"
    assert _pump(dfleet, fh.done.is_set)
    assert fh.new_tokens == _ref_new(model, params, p, 12)
    # prefill dispatch + handoff attach + migration attach
    assert fh.restart_consistent and fh.attempts == 3


def test_pool_pressure_signals(dfleet):
    assert dfleet.pool_pressure("prefill") >= 0.0
    assert dfleet.pool_pressure("decode") >= 0.0


# -- churn: cancel + prefill death mid-handoff (dedicated fleets) --------


def test_cancel_mid_handoff_leaks_nothing(model, params):
    """A parked export (decode pool full) that gets cancelled is
    dropped by the handoff sweep with terminal accounting and zero
    block leakage on either side."""
    reps = [
        Replica(0, model, params, _scfg(num_slots=1), max_len=MAX_LEN,
                pool="prefill").start(threaded=False),
        Replica(1, model, params, _scfg(num_slots=1), max_len=MAX_LEN,
                pool="decode").start(threaded=False),
    ]
    router = Router(config=FleetConfig(
        replicas=2, disagg=True, prefill_pool=1, decode_pool=1,
    ))
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(17)
    pa, pb = _prompt(rng, n=8), _prompt(rng, n=8)
    fa = router.submit(Request(
        prompt=pa, max_new_tokens=10, temperature=0.0,
    ))
    # Seat A on the (only) decode slot first.
    assert _pump(router, lambda: (
        fa.status == "running" and fa.replica_id == 1
    ))
    fb = router.submit(Request(
        prompt=pb, max_new_tokens=6, temperature=0.0,
    ))
    # B prefills, exports, and parks: the decode pool has no room.
    assert _pump(router, lambda: len(router._pending_handoffs) == 1)
    cancelled_pre = router.stats["cancelled"]
    fb.cancel()
    assert _pump(router, fb.done.is_set)
    assert fb.finish_reason == "cancelled"
    assert router.stats["cancelled"] == cancelled_pre + 1
    assert not router._pending_handoffs
    # A is untouched by the drop and finishes bitwise.
    assert _pump(router, fa.done.is_set)
    assert fa.new_tokens == _ref_new(model, params, pa, 10)
    # Ledger parity: directory pins released -> both allocators at rest.
    _release_directory(router)
    for r in reps:
        assert _ledger_balanced(r), f"replica {r.rid} leaked blocks"
    router.close()


def test_prefill_death_mid_handoff_is_lossless(model, params):
    """Kill one of two prefill replicas mid-storm: collected exports
    outlive their producer (host data), running prefills replay on the
    survivor, every stream completes bitwise, and the survivors'
    ledgers balance after the directory releases its pins."""
    pools = ("prefill", "prefill", "decode")
    reps = [
        Replica(k, model, params, _scfg(), max_len=MAX_LEN,
                pool=pools[k]).start(threaded=False)
        for k in range(3)
    ]
    router = Router(config=FleetConfig(
        replicas=3, disagg=True, prefill_pool=2, decode_pool=1,
    ))
    for r in reps:
        router.add_replica(r, start=False)
    rng = np.random.RandomState(19)
    cases = []
    for i in range(8):
        p = _prompt(rng, n=4 + (i % 5))
        cases.append((p, 3 + (i % 4), router.submit(Request(
            prompt=p, max_new_tokens=3 + (i % 4), temperature=0.0,
        ))))
    for _ in range(2):
        router.step()
    router.fail_replica(0, error=RuntimeError("chaos: pump died"))
    assert not router.directory.mapped_blocks(0), (
        "directory must never map blocks on a dead replica"
    )
    handles = [fh for _, _, fh in cases]
    assert _pump(router, lambda: all(h.done.is_set() for h in handles))
    for p, n, fh in cases:
        assert fh.finish_reason in ("eos", "length")
        assert fh.new_tokens == _ref_new(model, params, p, n)[
            : len(fh.new_tokens)
        ]
        assert fh.restart_consistent, f"request {fh.id} splice diverged"
    _release_directory(router)
    for r in reps[1:]:  # replica 0 is dead; its engine is not trusted
        assert _ledger_balanced(r), f"replica {r.rid} leaked blocks"
    router.close()


# -- per-pool autoscale ---------------------------------------------------


def test_controller_per_pool_watermarks(model, params):
    """A prefill burst scales the prefill pool (factory told which
    pool to build for) and a later prefill lull drains it — the decode
    pool's replica count never moves."""
    reps = [
        Replica(0, model, params, _scfg(), max_len=MAX_LEN,
                pool="prefill").start(threaded=False),
        Replica(1, model, params, _scfg(), max_len=MAX_LEN,
                pool="decode").start(threaded=False),
    ]
    router = Router(config=FleetConfig(
        replicas=2, disagg=True, prefill_pool=1, decode_pool=1,
    ))
    for r in reps:
        router.add_replica(r, start=False)
    built = []

    def factory(rid, pool):
        built.append((rid, pool))
        return Replica(rid, model, params, _scfg(), max_len=MAX_LEN,
                       pool=pool)

    pressures = {"prefill": 2.0, "decode": 0.5}
    wm = dict(high_pressure=1.0, low_pressure=0.3, up_ticks=2,
              down_ticks=2)
    ctl = FleetController(
        router, factory,
        ControllerConfig(pools={
            "prefill": PoolWatermarks(min_replicas=1, max_replicas=2,
                                      **wm),
            "decode": PoolWatermarks(min_replicas=1, max_replicas=1,
                                     **wm),
        }),
        reader=lambda pool=None: pressures.get(pool),
        threaded_replicas=False,
    )
    assert ctl.tick() is None          # prefill hot streak 1
    assert ctl.tick() == "scale_up"    # streak 2 -> grow prefill pool
    assert built == [(2, "prefill")]
    assert router._replica(2).pool == "prefill"
    def count(pool):
        return sum(1 for r in router.replicas
                   if r.pool == pool and r.state in ("starting", "ready"))
    assert count("prefill") == 2 and count("decode") == 1
    pressures["prefill"] = 0.1         # the burst ends
    assert ctl.tick() is None          # cold streak 1
    assert ctl.tick() == "drain"       # streak 2 -> drain a prefill
    assert _pump(router, lambda: any(
        r.state == "drained" for r in router.replicas
    ), limit=200)
    assert ctl.tick() == "remove"
    assert count("prefill") == 1 and count("decode") == 1
    pool_actions = [a for a in ctl.actions if "pool" in a]
    assert pool_actions and all(
        a["pool"] == "prefill" for a in pool_actions
    ), f"decode pool was touched: {ctl.actions}"
    # Without an injected reader, per-pool reads route to the router's
    # pool_pressure signal.
    ctl2 = FleetController(router, factory)
    assert ctl2.read_pressure("decode") == pytest.approx(
        router.pool_pressure("decode")
    )
    router.close()
