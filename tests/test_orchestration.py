"""Orchestration-layer tests: command construction + CLI dry runs.

The reference's L4 tier (provision / submit / stream notebooks) has no
tests at all; here every gcloud command line is asserted, and the CLIs
run end-to-end in --dry-run mode (which is also the documented way to
inspect what would run — docs/ORCHESTRATION.md).
"""

import json

import pytest

from distributeddeeplearning_tpu.orchestration import provision, submit


def test_storage_commands():
    cmds = provision.storage_commands(
        "my-imagenet", "tfrecords/", location="us-west4", project="proj"
    )
    assert cmds[0][:4] == ["gcloud", "storage", "buckets", "create"]
    assert "gs://my-imagenet" in cmds[0]
    assert "--project=proj" in cmds[0]
    assert cmds[1][:3] == ["gcloud", "storage", "rsync"]
    assert "gs://my-imagenet/data" in cmds[1]


def test_pod_lifecycle_commands():
    c = provision.pod_create_command(
        "pod", "us-west4-a", accelerator_type="v5litepod-64", spot=True
    )
    joined = " ".join(c)
    assert "tpu-vm create pod" in joined
    assert "--accelerator-type=v5litepod-64" in c
    assert "--spot" in c
    assert "--zone=us-west4-a" in c
    d = provision.pod_describe_command("pod", "z")
    assert "describe" in d
    x = provision.pod_delete_command("pod", "z")
    assert "delete" in x and "--quiet" in x


def test_setup_commands_pip_and_image():
    cmds = provision.setup_commands("pod", "z", bucket="my-imagenet")
    joined = [" ".join(c) for c in cmds]
    assert all("--worker=all" in j for j in joined)
    # code staging (reference 01_Train cell 11's upload-scripts step)
    assert any(" scp " in f" {j} " and "pod:~/ddl" in j for j in joined)
    assert any("pip install" in j and "-e ~/ddl" in j for j in joined)
    assert any("gs://my-imagenet/data" in j for j in joined)
    assert "jax.distributed.initialize" in joined[-1]  # acceptance check
    img = provision.setup_commands("pod", "z", image="gcr.io/p/ddl-tpu")
    assert any("docker pull gcr.io/p/ddl-tpu" in " ".join(c) for c in img)
    assert not any("pip install" in " ".join(c) for c in img)


def test_submit_inside_container_matches_setup_image():
    cmd = submit.submit_commands(
        "j2", "examples/imagenet_keras_tpu.py", (),
        tpu="pod", zone="z", detach=True, image="gcr.io/p/ddl-tpu",
    )
    joined = " ".join(cmd)
    assert "docker run --rm --name ddl-job-j2 --privileged --net=host" in joined
    assert "gcr.io/p/ddl-tpu" in joined
    assert "-e DISTRIBUTED=True" in joined
    assert "logs/j2.log" in joined  # detach still logs on the host side


def test_provision_cli_dry_run(capsys, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # .env writes stay in tmp
    rc = provision.main(
        ["--tpu", "pod", "--zone", "z", "--dry-run", "pod-create"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "gcloud compute tpus tpu-vm create pod" in out


def test_provision_cli_env_defaults(capsys, tmp_path):
    env = tmp_path / ".env"
    env.write_text("TPU_NAME=envpod\nZONE=envzone\nPROJECT=envproj\n")
    rc = provision.main(
        ["--env-file", str(env), "--dry-run", "pod-status"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "describe envpod" in out
    assert "--zone=envzone" in out
    assert "--project=envproj" in out


def test_submit_foreground_and_detached():
    fg = submit.submit_commands(
        "j1", "examples/imagenet_keras_tpu.py", ("--x",),
        tpu="pod", zone="z", env={"FAKE": "True"},
    )
    joined = " ".join(fg)
    assert "--worker=all" in joined
    assert "DISTRIBUTED=True" in joined and "FAKE=True" in joined
    assert "python3 -u examples/imagenet_keras_tpu.py" in joined

    det = submit.submit_commands(
        "j1", "train.py", (), tpu="pod", zone="z", detach=True,
    )
    joined = " ".join(det)
    # `nohup env K=V python` — nohup cannot exec a bare K=V assignment
    assert "nohup env " in joined
    assert "logs/j1.log" in joined
    assert "logs/j1.pid" in joined


def test_stream_and_control_commands():
    s = submit.stream_command("j1", tpu="pod", zone="z", worker="3")
    assert "--worker=3" in s
    assert any("tail -f" in c and "logs/j1.log" in c for c in s)
    s2 = submit.stream_command("j1", tpu="pod", zone="z", follow=False)
    assert not any("tail -f" in c for c in s2)
    st = submit.control_command("j1", "status", tpu="pod", zone="z")
    # must handle both host-pid jobs and containerized (--image) jobs
    assert any("sudo kill -0" in c and "docker ps" in c for c in st)
    sp = submit.control_command("j1", "stop", tpu="pod", zone="z")
    assert any(
        "sudo docker stop ddl-job-j1" in c and "sudo kill $(cat" in c
        for c in sp
    )
    with pytest.raises(ValueError):
        submit.control_command("j1", "bogus", tpu="pod", zone="z")


def test_submit_cli_writes_manifest(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    manifest = tmp_path / "job.json"
    rc = submit.main(
        [
            "--tpu", "pod", "--zone", "z", "--dry-run",
            "run", "--job", "rn50", "--detach",
            "--env", "EPOCHS=90",
            "--manifest", str(manifest),
            "examples/imagenet_keras_tpu.py",
        ]
    )
    assert rc == 0
    data = json.loads(manifest.read_text())
    assert data["job"] == "rn50"
    assert data["tpu"] == "pod"
    assert data["env"] == {"EPOCHS": "90"}
    assert data["detach"] is True
    assert "nohup" in data["command"]
    out = capsys.readouterr().out
    assert "gcloud compute tpus tpu-vm ssh pod" in out


def test_makefile_targets_exist():
    import os, re, subprocess, sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    text = open(os.path.join(repo, "Makefile")).read()
    for target in (
        "build", "push", "run", "smoke", "test", "bench",
        "provision", "setup", "submit", "stream", "status", "stop",
        "teardown",
    ):
        assert re.search(rf"^{target}:", text, re.M), target
    # make -n parses the file and expands a cluster target
    res = subprocess.run(
        ["make", "-n", "submit", "TPU=pod", "ZONE=z", f"PY={sys.executable}"],
        cwd=repo, capture_output=True, text=True,
    )
    assert res.returncode == 0, res.stderr
    assert "orchestration.submit" in res.stdout


def test_dockerfile_mentions_tpu_stack():
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    text = open(os.path.join(repo, "Dockerfile")).read()
    assert "jax[tpu]" in text
    assert "launch.py" in text  # smoke CMD = the 2-process run


def test_notebook_front_end_is_valid_and_covers_lifecycle():
    import json, os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "notebooks", "01_ProvisionAndTrain.ipynb")
    nb = json.load(open(path))
    assert nb["nbformat"] == 4
    src = "".join(
        "".join(c["source"]) for c in nb["cells"] if c["cell_type"] == "code"
    )
    for needle in (
        "orchestration.provision", "orchestration.submit",
        "pod-create", "setup", "run --detach", "stream", "pod-delete",
        "data.prepare",
    ):
        assert needle in src, needle


def test_smoke_and_frontend_notebooks_are_valid():
    """The round-3 notebooks: valid nbformat, and their code matches the
    APIs/Makefile targets they claim to drive (00: repo-only IMAGE +
    build/run targets; 02: code cells actually compile)."""
    import json, os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    nb0 = json.load(
        open(os.path.join(repo, "notebooks", "00_BuildImageAndSmoke.ipynb"))
    )
    src0 = "".join(
        "".join(c["source"]) for c in nb0["cells"] if c["cell_type"] == "code"
    )
    assert "make build IMAGE=" in src0 and "make run IMAGE=" in src0
    assert "launch.py -n 2" in src0
    # IMAGE must be a repo name only (the Makefile appends ':TAG')
    for line in src0.splitlines():
        if line.startswith("IMAGE ="):
            value = line.split("=", 1)[1].split("#")[0]
            assert ":" not in value, line

    nb2 = json.load(
        open(os.path.join(repo, "notebooks", "02_TrainFrontends.ipynb"))
    )
    code = [
        "".join(c["source"]) for c in nb2["cells"] if c["cell_type"] == "code"
    ]
    for i, cell in enumerate(code):
        compile(cell, f"02_TrainFrontends cell {i}", "exec")  # syntax-valid
    joined = "".join(code)
    for needle in ("keras_style", "Estimator", "explicit.setup",
                   "loop.fit", "pp_schedule='1f1b'"):
        assert needle in joined, needle


def test_multislice_create_command_and_cli(capsys, tmp_path, monkeypatch):
    """--slices N provisions one queued resource with N DCN-connected
    slices (round 5; trains with MESH_AXES=replica,data over the hybrid
    mesh), and the dry-run plan includes the ACTIVE-wait poll (the
    queued create returns at ACCEPTED, unlike the blocking tpu-vm
    create)."""
    c = provision.multislice_create_command(
        "ms", "us-west4-a", num_slices=4, accelerator_type="v5litepod-16"
    )
    joined = " ".join(c)
    assert "queued-resources create ms" in joined
    assert "--node-count=4" in c
    assert "--accelerator-type=v5litepod-16" in c
    monkeypatch.chdir(tmp_path)
    rc = provision.main(
        ["--tpu", "ms", "--zone", "z", "--dry-run", "pod-create",
         "--slices", "2"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "queued-resources create ms" in out and "--node-count=2" in out
    assert "poll until ACTIVE" in out
    # --slices 1 keeps the plain tpu-vm create path
    rc = provision.main(
        ["--tpu", "ms", "--zone", "z", "--dry-run", "pod-create"]
    )
    assert rc == 0
    assert "tpu-vm create ms" in capsys.readouterr().out


def test_multislice_lifecycle_targets_queued_resource(capsys, tmp_path,
                                                      monkeypatch):
    """status/delete/setup on a multi-slice pod must target the queued
    resource (delete --force tears down its slices; tpu-vm commands
    would 404 — the nodes are named ms-0…ms-(N-1))."""
    monkeypatch.chdir(tmp_path)
    assert provision.multislice_node_names("ms", 2) == ["ms-0", "ms-1"]
    for argv, want in (
        (["--tpu", "ms", "--zone", "z", "--dry-run", "pod-status",
          "--slices", "2"], "queued-resources describe ms"),
        (["--tpu", "ms", "--zone", "z", "--dry-run", "pod-delete",
          "--slices", "2"], "queued-resources delete ms"),
    ):
        assert provision.main(argv) == 0
        assert want in capsys.readouterr().out
    # setup fans the full bring-up out over every node
    assert provision.main(
        ["--tpu", "ms", "--zone", "z", "--dry-run", "setup", "--slices", "2"]
    ) == 0
    out = capsys.readouterr().out
    assert "ms-0:" in out and "ms-1:" in out  # scp staging per node
    # delete --force --quiet present on the queued-resource delete
    d = provision.multislice_delete_command("ms", "z")
    assert "--force" in d and "--quiet" in d


def test_multislice_slices_recorded_and_read_from_env(capsys, tmp_path,
                                                      monkeypatch):
    """pod-create records SLICES in .env (alongside TPU_NAME/ZONE) and
    later lifecycle verbs read it back without an explicit --slices."""
    monkeypatch.chdir(tmp_path)
    called = []
    monkeypatch.setattr(
        provision, "run_pod_create", lambda cmd, dry_run, sink=None:
        called.append(tuple(cmd)) or 0,
    )
    monkeypatch.setattr(
        provision, "wait_for_multislice",
        lambda *a, **k: 0,
    )
    rc = provision.main(
        ["--tpu", "ms", "--zone", "z", "pod-create", "--slices", "2"]
    )
    assert rc == 0 and "--node-count=2" in called[0]
    env = (tmp_path / ".env").read_text()
    assert "SLICES=2" in env and "TPU_NAME=ms" in env
    # no --slices flag: pod-status picks the env record up
    assert provision.main(["--zone", "z", "--dry-run", "pod-status"]) == 0
    assert "queued-resources describe ms" in capsys.readouterr().out
