"""Unit tests for the host-sync accounting layer (utils/hostsync.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributeddeeplearning_tpu.utils import hostsync


def test_accountant_counts_and_labels():
    acct = hostsync.accountant()
    acct.reset()
    x = jnp.arange(4.0)
    y = hostsync.device_get(x, label="alpha")
    hostsync.device_get(x, label="alpha")
    hostsync.device_get({"a": x, "b": x}, label="beta")  # one tree = one sync
    np.testing.assert_array_equal(y, np.arange(4.0))
    assert acct.count == 3
    assert acct.by_label == {"alpha": 2, "beta": 1}
    acct.reset()
    assert acct.count == 0 and acct.by_label == {}


def test_track_counts_raw_device_get_without_double_counting():
    acct = hostsync.accountant()
    acct.reset()
    x = jnp.ones((2,))
    with hostsync.track() as tracked:
        jax.device_get(x)  # raw call: counted by the patch
        hostsync.device_get(x, label="wrapped")  # counted ONCE, not twice
    assert tracked is acct
    assert acct.count == 2, acct.by_label
    assert acct.by_label["jax.device_get"] == 1
    assert acct.by_label["wrapped"] == 1
    # patch removed on exit
    before = acct.count
    jax.device_get(x)
    assert acct.count == before


def test_step_clock_percentiles_and_wait():
    clock = hostsync.StepClock()
    for ms in (1, 2, 3, 4, 100):
        clock.note_dispatch(ms / 1e3)
    with clock.waiting():
        pass
    s = clock.summary()
    assert s["steps"] == 5
    assert s["dispatch_p50_ms"] == 3.0
    assert s["dispatch_p99_ms"] == 100.0
    assert s["wait_total_s"] >= 0.0
    assert abs(s["dispatch_total_s"] - 0.110) < 1e-9


def test_step_clock_empty_summary():
    s = hostsync.StepClock().summary()
    assert s["steps"] == 0 and s["dispatch_p99_ms"] == 0.0
