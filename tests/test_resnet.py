import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddeeplearning_tpu.models import get_model, available_models
from distributeddeeplearning_tpu.models.resnet import ResNet, resnet_v1


def _init(model, size=32):
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((2, size, size, 3), jnp.float32)
    return model.init(rng, x, train=False), x


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def test_registry_has_resnet_family():
    names = available_models()
    for d in (18, 34, 50, 101, 152, 200):
        assert f"resnet{d}" in names


def test_forward_shape_fp32_logits():
    model = get_model("resnet18", num_classes=10)
    variables, x = _init(model)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_resnet50_param_count_matches_reference():
    # torchvision resnet50 (the reference PyTorch model,
    # imagenet_pytorch_horovod.py:323) has 25,557,032 params; our v1
    # builder must match exactly (same architecture, bias-free convs).
    model = ResNet(depth=50, num_classes=1000, dtype=jnp.float32)
    variables, _ = _init(model, size=64)
    assert _param_count(variables["params"]) == 25_557_032


def test_resnet18_param_count_matches_reference():
    model = ResNet(depth=18, num_classes=1000, dtype=jnp.float32)
    variables, _ = _init(model, size=64)
    assert _param_count(variables["params"]) == 11_689_512  # torchvision resnet18


def test_zero_init_residual_gamma():
    # reference resnet_model.py:150,201 zero-inits the last BN gamma of
    # each residual branch.
    model = ResNet(depth=18, num_classes=10)
    variables, _ = _init(model)
    bn2 = variables["params"]["stage1_block1"]["BatchNorm_1"]
    np.testing.assert_array_equal(np.asarray(bn2["scale"]), 0.0)


def test_bad_depth_raises():
    model = ResNet(depth=77)
    with pytest.raises(ValueError, match="depth"):
        _init(model)


def test_resnet_v1_factory():
    m = resnet_v1(34, num_classes=7)
    assert m.depth == 34 and m.num_classes == 7


def test_batch_stats_update_in_train_mode():
    model = ResNet(depth=18, num_classes=10)
    variables, x = _init(model)
    x = jax.random.normal(jax.random.PRNGKey(1), x.shape)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_bfloat16_compute_f32_params():
    model = ResNet(depth=18, num_classes=10, dtype=jnp.bfloat16)
    variables, x = _init(model)
    for leaf in jax.tree.leaves(variables["params"]):
        assert leaf.dtype == jnp.float32
    out = model.apply(variables, x, train=False)
    assert out.dtype == jnp.float32


def test_perf_knobs_bf16_stats_and_s2d_stem():
    # PROFILE.md roadmap knobs (measured no-win on v5e but supported):
    # bf16 statistics reduction + MLPerf space-to-depth stem.
    model = ResNet(depth=18, num_classes=10, dtype=jnp.bfloat16,
                   stats_dtype=jnp.bfloat16, s2d_stem=True)
    variables, x = _init(model, size=64)
    stem = variables["params"]["stem_conv_s2d"]["kernel"]
    assert stem.shape == (4, 4, 12, 64)  # 112²×12 input, 2× fold into channels
    out, mutated = model.apply(variables, jnp.asarray(x, jnp.bfloat16),
                               train=True, mutable=["batch_stats"])
    assert out.shape == (2, 10) and out.dtype == jnp.float32
    # running stats stay f32 regardless of the reduction dtype
    for leaf in jax.tree.leaves(mutated["batch_stats"]):
        assert leaf.dtype == jnp.float32
